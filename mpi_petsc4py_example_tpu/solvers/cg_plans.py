"""Composable CG loop-body plans (ROADMAP items 2+5, landed together).

``krylov.py`` used to carry five hand-fused copies of the CG recurrence
(plain / stencil / many / guarded / guarded-many), and every new axis —
pipelined, batched, guarded, grid-shaped — multiplied the matrix again.
This module factors the recurrence into orthogonal *plans* assembled into
ONE ``lax.while_loop`` body per recurrence family:

* **operator-apply plan** — the (possibly fused-dot) operator closure:
  ``A(v)`` for general operators, ``Adot(v) -> (Av, psum<v,Av>)`` for the
  VMEM-resident stencil fast path;
* **PC plan** — how the preconditioned direction is produced: a
  materialized ``z = M r``, the scalar uniform-diagonal identity
  (``z = r/diag`` never materialized), or the 3D-native V-cycle ``M3``;
* **reduction plan** — how the iteration's inner products map onto psum
  SITES: classic 3-site (2 under the natural norm), the fused 2-site
  stacked pair, the guarded 2-site phases with the ABFT partials folded
  in, the PIPELINED 1-site plan (:func:`pipelined_cg_loop`) whose one
  stacked psum is overlapped against the next SpMV/PC apply, or the
  S-STEP communication-avoiding plan (:func:`sstep_cg_loop`) whose one
  stacked Gram psum serves s whole iterations;
* **guard plan** — ``None``, or the silent-corruption bookkeeping
  (NaN/monotonicity sentinels, periodic true-residual replacement with
  the drift gate, ``det``/``rrc``/verified-iterate outputs);
* **batching plan** — :class:`SingleBatch` / :class:`ManyBatch`: scalar
  broadcasting, per-column mask selects, and loop-condition aggregation.

The assembled bodies reproduce the retired kernels' arithmetic exactly
(masked selects with an always-true mask are the identity), so iteration
counts, reasons, and the collective-volume gates are unchanged — and
pipelined CG (Ghysels & Vanroose; PETSc's KSPPIPECG slot) lands as a new
reduction plan rather than a sixth kernel family.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax


# ---------------------------------------------------------------------------
# precision plans: the storage-vs-reduce dtype axis (PR 10)
# ---------------------------------------------------------------------------


class PrecisionPlan:
    """The precision axis of a compute plan: ``storage`` is the
    operator/PC/iterate channel's dtype (what the all-gathers, halo
    ppermutes, and AXPY traffic move — halving it halves the bytes per
    iterate), ``reduce`` the dot-product/norm/ABFT accumulation channel's
    dtype (kept wider, the pipelined-Krylov reduction-channel discipline).

    With ``storage == reduce`` (fp32/fp64/complex operators) every hook
    is the identity and the assembled loop bodies are the pre-plan ones
    bit for bit — the collective-volume and reduce-site gates see
    identical programs. The MIXED case (bf16 storage, fp32 reduce) casts
    each vector update back to storage (``store``) and lifts reduction
    operands up (``up``); scalars (alpha/beta/rz/norms) live in the
    reduce dtype throughout the carry.
    """

    def __init__(self, storage, reduce=None):
        from ..utils import dtypes as _dtypes
        self.storage = np.dtype(storage)
        self.reduce = np.dtype(reduce if reduce is not None
                               else _dtypes.reduce_dtype(self.storage))
        self.mixed = self.reduce != self.storage

    def store(self, v):
        """Cast a vector update back to the storage channel (identity
        for uniform-precision plans — no-op in the lowered HLO)."""
        return v.astype(self.storage) if self.mixed else v

    def up(self, v):
        """Lift a reduction operand into the accumulation channel."""
        return v.astype(self.reduce) if self.mixed else v

    def key(self):
        """The (storage, reduce) fingerprint compiled-program caches and
        serving compatibility keys carry."""
        return (str(self.storage), str(self.reduce))

    def __repr__(self):
        return f"PrecisionPlan(storage={self.storage}, reduce={self.reduce})"


def precision_plan(storage, reduce=None) -> PrecisionPlan:
    """Build the precision plan for an operator's storage dtype (the
    reduce dtype defaults to utils.dtypes.reduce_dtype: fp32 for
    sub-32-bit storage, the storage dtype itself otherwise)."""
    return PrecisionPlan(storage, reduce)


def _stc(prec):
    """The store-channel cast of a plan (identity without one)."""
    if prec is not None and prec.mixed:
        return prec.store
    return lambda v: v

# ---------------------------------------------------------------------------
# shared numeric helpers (moved here from krylov.py so both modules — and
# every plan — read ONE definition; krylov re-exports them unchanged)
# ---------------------------------------------------------------------------


def _dmax(rnorm0, dtol):
    """Divergence ceiling: ``dtol * rnorm0`` — the INITIAL residual norm, as
    in PETSc's KSPConvergedDefault DIVERGED_DTOL test (a merely-large initial
    guess must not trigger instant divergence). ``dtol`` None/<=0 disables."""
    if dtol is None:
        return jnp.inf
    return jnp.where(dtol > 0, dtol * rnorm0, jnp.inf)


def _tol(pnorm, b, rtol, atol):
    bnorm = pnorm(b)
    return bnorm, jnp.maximum(rtol * bnorm, atol)


def _nat(rz):
    """KSP_NORM_NATURAL: sqrt <r, M r> — the scalar the CG-family
    recurrences already carry (real by construction for the SPD/Hermitian
    operators these types require)."""
    return jnp.sqrt(jnp.maximum(jnp.real(rz), 0.0))


def _reason(rnorm, tol, atol, k, maxit, brk, dmax=None):
    from ..utils.convergence import ConvergedReason as CR
    diverged = (CR.DIVERGED_MAX_IT if dmax is None else
                jnp.where(rnorm >= dmax, CR.DIVERGED_DTOL,
                          CR.DIVERGED_MAX_IT))
    return jnp.where(
        brk, CR.DIVERGED_BREAKDOWN,
        jnp.where(rnorm <= tol,
                  jnp.where(rnorm <= atol, CR.CONVERGED_ATOL,
                            CR.CONVERGED_RTOL),
                  diverged)).astype(jnp.int32)


def _no_hist(dtype):
    """Zero-size placeholder carried when monitoring is off — compiled
    away entirely, but keeps every kernel's carry structure uniform."""
    return jnp.zeros((0,), jnp.real(jnp.zeros((), dtype)).dtype)


def _hist0(monitor, dtype):
    """The history carry every kernel threads through its loop: the real
    recorder when monitoring, a zero-size placeholder otherwise."""
    return monitor.init() if monitor is not None else _no_hist(dtype)


def _mon0(monitor, rn0, dtype):
    """Build the history carry and record the iteration-0 (initial)
    residual norm. petsc4py's monitors and KSPSetResidualHistory include
    it — history length is iterations+1, and drivers index history[0] for
    the starting norm."""
    hist = _hist0(monitor, dtype)
    if monitor is not None:
        return monitor(hist, jnp.int32(0), rn0)
    return hist


# ---------------------------------------------------------------------------
# silent-data-corruption detector codes + thresholds (single source; the
# guarded plans and solvers/ksp.py both read these via krylov's re-export)
# ---------------------------------------------------------------------------

(SDC_NONE, SDC_ABFT, SDC_ABFT_PC, SDC_DRIFT, SDC_NAN, SDC_MONO,
 SDC_DEMOTE) = range(7)
SDC_DETECTOR_NAMES = {SDC_ABFT: "abft", SDC_ABFT_PC: "abft_pc",
                      SDC_DRIFT: "drift", SDC_NAN: "nan",
                      SDC_MONO: "monotonic",
                      # NOT a corruption code: the s-step plan's drift gate
                      # exhausted its basis-restart budget
                      # (-ksp_sstep_max_replacements) — the host demotes
                      # the solve to classic CG from the current iterate
                      # instead of rolling back (solvers/ksp.py)
                      SDC_DEMOTE: "sstep_demote"}

# monotonicity sentinel: a residual norm this far above the best seen so
# far is beyond any healthy CG transient (bounded by sqrt(cond(A)))
_SDC_MONO_FACTOR = 1e4
# drift gate: recurrence-vs-true relative mismatch beyond this fraction
# (plus a rounding floor of _SDC_DRIFT_FLOOR_EPS * eps * ||b||) flags SDC
_SDC_DRIFT_REL = 0.25
_SDC_DRIFT_FLOOR_EPS = 1024.0

# s-step coordinate-resolution floor: the in-block residual² is computed
# as a DIFFERENCE of O(‖r_block_start‖²) Gram quadratics, so its absolute
# noise is ~eps·‖r₀‖²·O(m) — below _SSTEP_RR_FLOOR·m·eps·rr0 the value is
# rounding, the block freezes, and the next block restarts from the
# full-precision materialized residual (whose ‖·‖² the Gram psums
# DIRECTLY, restoring resolution). Caps the per-block reduction at
# ~16·sqrt(m·eps)× — deeper convergence just takes another block.
_SSTEP_RR_FLOOR = 256.0

# s-step stagnation gate: CA-CG basis ill-conditioning does NOT show up
# as r-vs-true drift (x and r are combined from the SAME coordinate
# vector, so they stay consistent by construction) — it shows up as the
# TRUE residual stalling while the coordinate recurrences spin. A
# replacement check that finds less than this reduction factor since the
# LAST check declares the basis ineffective at this s.
_SSTEP_STALL_FACTOR = 0.9


def _det4(badA, badM, badnan, badmono):
    """First-detector-wins detection code (elementwise for batched)."""
    return jnp.where(
        badA, SDC_ABFT,
        jnp.where(badM, SDC_ABFT_PC,
                  jnp.where(badnan, SDC_NAN,
                            jnp.where(badmono, SDC_MONO,
                                      SDC_NONE)))).astype(jnp.int32)


# ---------------------------------------------------------------------------
# batching plans
# ---------------------------------------------------------------------------


class SingleBatch:
    """One RHS: scalars are scalars, the continuation mask broadcasts
    trivially, and the loop condition is the mask itself."""

    many = False

    def ex(self, s):
        return s

    def agg(self, m):
        return m


class ManyBatch:
    """``nrhs`` lockstep recurrences: per-column ``(nrhs,)`` scalars, a
    column mask broadcast against the vector-block layout, and the loop
    running until the LAST active column exits.

    ``layout='cols'`` is the flat ``(lsize, nrhs)`` block (mask/scalars
    expand as ``s[None, :]``); ``layout='slabs'`` the grid-shaped
    ``(nrhs, lz, ny, nx)`` stencil block (``s[:, None, None, None]``).
    """

    many = True

    def __init__(self, layout: str = "cols"):
        if layout not in ("cols", "slabs"):
            raise ValueError(f"unknown ManyBatch layout {layout!r}")
        self._cols = layout == "cols"

    def ex(self, s):
        return s[None, :] if self._cols else s[:, None, None, None]

    def agg(self, m):
        return jnp.any(m)


def _false_like(rn):
    return jnp.zeros(jnp.shape(rn), bool)


def _it0(rn):
    return jnp.zeros(jnp.shape(rn), jnp.int32)


# ---------------------------------------------------------------------------
# the pipelined plan's single reduce site (test-injection seam)
# ---------------------------------------------------------------------------


def fuse_psum(parts, psum, axis, dtype):
    """ONE stacked collective for ALL of a pipelined iteration's scalar
    reductions — the 1-reduce-site contract of the pipelined plan.

    Kept as a module-level seam on purpose: the collective-volume gate's
    injected-regression test monkeypatches this into a two-psum split to
    prove the one-site assert has teeth. ``parts`` may be per-column
    ``(nrhs,)`` rows; everything is cast to the operator scalar so the
    stack is homogeneous (the callers re-take real parts of norms)."""
    return psum(jnp.stack([jnp.asarray(q, dtype) for q in parts]), axis)


def fuse_gram_psum(parts, psum, axis, dtype, batched=False):
    """ONE stacked collective for an s-step block's whole reduction
    payload — the tall-skinny Gram matrix plus every guard partial.

    ``parts`` is a list of arrays with mixed leading shapes (the
    ``(q, q[, nrhs])`` Gram block, ``(m[, nrhs])`` checksum rows,
    scalars); each is flattened over its leading (non-batch) dims,
    concatenated into one stack, reduced in a SINGLE psum, and split
    back to the input shapes. This is the s-step plan's 1-reduce-site
    contract (one collective per s iterations) and, like
    :func:`fuse_psum`, a deliberate module-level seam: the
    collective-volume gate's injected-regression test monkeypatches it
    into a two-psum split to prove the one-site assert has teeth.

    ``batched=True`` declares a trailing ``(nrhs,)`` batch axis on every
    part (the ManyBatch layout), preserved through the flatten.
    """
    parts = [jnp.asarray(p, dtype) for p in parts]
    tail_n = 1 if batched else 0
    tail = parts[0].shape[parts[0].ndim - tail_n:]
    flat = []
    lead_shapes = []
    for p in parts:
        lead = p.shape[: p.ndim - tail_n]
        lead_shapes.append(lead)
        flat.append(p.reshape((-1,) + tail))
    stacked = psum(jnp.concatenate(flat, axis=0), axis)
    out = []
    at = 0
    for p, lead in zip(flat, lead_shapes):
        rows = p.shape[0]
        out.append(stacked[at:at + rows].reshape(lead + tail))
        at += rows
    return out


# ---------------------------------------------------------------------------
# classic CG: one while_loop body serving plain/stencil/many/guarded
# ---------------------------------------------------------------------------


def classic_cg_loop(*, b, x0, rtol, atol, maxit, dtol=None,
                    A=None, M=None, Adot=None, inv_diag=None, M3=None,
                    pdot=None, pnorm=None, pduo=None, guard=None,
                    bp=None, monitor=None, unroll=1, natural=False,
                    prec=None):
    """Assemble and run the classic (two-phase) CG recurrence.

    Plan axes (module docstring): the operator plan is ``A`` or the fused
    ``Adot``; the PC plan is ``M`` (materialized z), ``inv_diag`` (scalar
    uniform-diagonal identity) or ``M3`` (3D-native V-cycle); the
    reduction plan is implied by what is supplied — plain ``pdot``/
    ``pnorm`` (3 sites; 2 under ``natural``), the stacked ``pduo`` pair
    (2 sites), or a ``guard`` namespace whose ``p1``/``p2``/
    ``p2_stencil`` phases carry the folded ABFT partials (2 sites);
    ``bp`` is the batching plan. Per-column masked freezing, unrolled
    multi-step dispatch, and the guard's replacement/rollback bookkeeping
    are all specializations of this one body.

    Returns the retired kernels' exact output tuples:
    ``(x, it, rnorm, reason, hist)`` and, guarded,
    ``(..., det, rrc, xv)``.

    ``prec`` is the :class:`PrecisionPlan`: with a mixed plan the vector
    carries (x/r/p/z) stay in the storage dtype — every update that
    mixes in a reduce-dtype scalar is cast back through ``prec.store`` —
    while the reduction closures (supplied by the program builder) lift
    their operands into the reduce dtype, so alpha/beta/rz/norms travel
    wide. Uniform plans leave the body untouched.
    """
    bp = bp or SingleBatch()
    g = guard
    st_ = _stc(prec)
    stencil = Adot is not None
    carry_z = not stencil

    # ---- init: initial residual + the plan's init reductions ---------------
    if stencil:
        if g is not None:
            r = b - Adot(x0)[0]
            bnorm, rnorm, badA0 = g.init(b, r, x0)
            rz = rnorm * rnorm * inv_diag
            p = st_(r * inv_diag)
            badM0 = _false_like(rnorm)
        else:
            bnorm = pnorm(b)
            r = b - Adot(x0)[0]
            rr0 = pdot(r, r)
            rnorm = jnp.sqrt(rr0)
            if M3 is None:
                rz = rr0 * inv_diag
                p = st_(r * inv_diag)
            else:
                z0 = M3(r)
                rz = pdot(r, z0)
                p = z0
        tol = jnp.maximum(rtol * bnorm, atol)
        brk0 = _false_like(rnorm)
        z = None
    else:
        r = b - A(x0)
        if g is not None:
            bnorm, badA0 = g.init(b, r, x0)
            z = M(r)
            rz, rn2, badM0 = g.p2(r, z)
            rnorm = jnp.sqrt(jnp.maximum(jnp.real(rn2), 0.0))
            p = z
            tol = jnp.maximum(rtol * bnorm, atol)
            brk0 = _false_like(rnorm)
        else:
            z = M(r)
            p = z
            rz = pdot(r, z)
            if natural:
                rnorm = _nat(rz)
                tol = jnp.maximum(rtol * rnorm, atol)
                # a negative <r, M r> means M (or A) is indefinite — the
                # natural norm is undefined there; flag breakdown instead
                # of letting the 0-clamped norm fake instant convergence
                brk0 = jnp.real(rz) < 0
            else:
                bnorm, tol = _tol(pnorm, b, rtol, atol)
                rnorm = pnorm(r)
                brk0 = _false_like(rnorm)
    dmax = _dmax(rnorm, dtol)
    hist = _mon0(monitor, rnorm, b.dtype)

    st0 = dict(it=_it0(rnorm), x=x0, r=r, p=p, rz=rz, rn=rnorm, brk=brk0,
               hist=hist)
    if carry_z:
        st0["z"] = z
    if g is not None:
        drift_floor = _SDC_DRIFT_FLOOR_EPS * g.eps * bnorm
        st0.update(det=_det4(badA0, badM0, ~jnp.isfinite(rnorm),
                             _false_like(rnorm)),
                   rrc=_it0(rnorm), xv=x0, rnb=rnorm)
        if bp.many:
            # the lockstep STEP counter the replacement interval runs on
            # (per-column iteration counts diverge once columns freeze)
            st0["ks"] = jnp.int32(0)

    def active(st):
        live = ((st["rn"] > tol) & (st["rn"] < dmax) & (st["it"] < maxit)
                & ~st["brk"])
        if g is not None:
            live = live & (st["det"] == SDC_NONE)
        return live

    def cond(st):
        return bp.agg(active(st))

    def step(st):
        cont = active(st)
        cm = bp.ex(cont)
        it, x, r, p, rz = st["it"], st["x"], st["r"], st["p"], st["rz"]

        # ---- operator apply + reduction phase 1 ----
        if stencil:
            Ap, pAp = Adot(p)                  # fused matvec+dot (1 psum)
            badA = None
        elif g is not None:
            Ap = A(p)
            pAp, badA = g.p1(p, Ap)            # stacked phase 1 + A-ABFT
        else:
            Ap = A(p)
            pAp = pdot(p, Ap)                  # reduction phase 1
            badA = None
        brk_new = cont & (pAp == 0)
        alpha = jnp.where(pAp == 0, 0.0,
                          rz / jnp.where(pAp == 0, 1.0, pAp))
        # frozen steps/columns SELECT the old state rather than multiplying
        # by a zero gate: once a diverging active step has produced
        # inf/NaN, 0 * inf = NaN would destroy the preserved iterate
        al = bp.ex(alpha)
        x = jnp.where(cm, st_(x + al * p), x)
        r = jnp.where(cm, st_(r - al * Ap), r)

        # ---- PC apply + reduction phase 2 ----
        z = None
        badM = None
        if stencil:
            if g is not None:
                rr, badA = g.p2_stencil(r, p, Ap)   # fused phase 2 + ABFT
                rz_new = rr * inv_diag
                zdir = st_(r * inv_diag)
                rn_new = jnp.sqrt(rr)
            elif M3 is not None:
                rr = pdot(r, r)
                zn = M3(r)
                rz_new = pdot(r, zn)
                zdir = zn
                rn_new = jnp.sqrt(rr)
            else:
                rr = pdot(r, r)
                rz_new = rr * inv_diag
                zdir = st_(r * inv_diag)
                rn_new = jnp.sqrt(rr)
        else:
            z = jnp.where(cm, M(r), st["z"])
            zdir = z
            if g is not None:
                rz_new, rn2, badM = g.p2(r, z)      # stacked phase 2
                rn_new = jnp.sqrt(jnp.maximum(jnp.real(rn2), 0.0))
            elif pduo is not None:
                rz_new, rr = pduo(r, z)             # fused (rz, rr) pair
                rn_new = jnp.sqrt(jnp.maximum(jnp.real(rr), 0.0))
            else:
                rz_new = pdot(r, z)                 # reduction phase 2
                rn_new = None                       # phase 3 / natural below
        if natural and g is None and not stencil:
            brk_new = brk_new | (cont & (jnp.real(rz_new) < 0))
        beta = jnp.where(rz == 0, 0.0, rz_new / jnp.where(rz == 0, 1.0, rz))
        p = jnp.where(cm, st_(zdir + bp.ex(beta) * p), p)
        rz = jnp.where(cont, rz_new, rz)
        if rn_new is None:
            rn_new = _nat(rz_new) if natural else pnorm(r)
        rn = jnp.where(cont, rn_new, st["rn"])
        it = it + cont.astype(jnp.int32)

        st2 = dict(it=it, x=x, r=r, p=p, rz=rz, rn=rn,
                   brk=st["brk"] | brk_new, hist=st["hist"])
        if carry_z:
            st2["z"] = z

        # ---- guard plan: sentinels + periodic replacement ----
        if g is not None:
            if bp.many:
                badnan = cont & ~jnp.isfinite(rn)
                badmono = cont & jnp.isfinite(rn) & (rn > _SDC_MONO_FACTOR
                                                     * st["rnb"])
                rnb = jnp.where(cont & jnp.isfinite(rn),
                                jnp.minimum(st["rnb"], rn), st["rnb"])
                # STICKY per-column detection: a frozen column's code must
                # survive later passes (cont masks its checks once frozen)
                badA_m = cont & badA if badA is not None else badnan & False
                badM_m = cont & badM if badM is not None else badnan & False
                det = jnp.where(st["det"] == SDC_NONE,
                                _det4(badA_m, badM_m, badnan, badmono),
                                st["det"])
                ks = st["ks"] + 1
                clean = det == SDC_NONE
                do_rr = (jnp.any(cont & clean) & (g.rr_n > 0)
                         & (ks % jnp.maximum(g.rr_n, 1) == 0))
                st2["ks"] = ks
            else:
                badnan = ~jnp.isfinite(rn)
                badmono = jnp.isfinite(rn) & (rn > _SDC_MONO_FACTOR
                                              * st["rnb"])
                rnb = jnp.where(jnp.isfinite(rn),
                                jnp.minimum(st["rnb"], rn), st["rnb"])
                fA = badA if badA is not None else badnan & False
                fM = badM if badM is not None else badnan & False
                det = _det4(fA, fM, badnan, badmono)
                clean = det == SDC_NONE
                do_rr = ((det == SDC_NONE) & (g.rr_n > 0)
                         & (it % jnp.maximum(g.rr_n, 1) == 0) & (rn > tol))
            st2["rnb"] = rnb

            def replace(args):
                x, r, z, p, rz, rn, rrc, xv = args
                if stencil:
                    rt = b - Adot(x)[0]
                    rtn2 = g.vnorm2(rt)            # plain-psum verifier
                    rtn = jnp.sqrt(jnp.maximum(rtn2, 0.0))
                else:
                    rt = b - A(x)
                    zt = M(rt)
                    rtn2, rzt = g.vpair(rt, zt)    # plain-psum verifier
                    rtn = jnp.sqrt(jnp.maximum(rtn2, 0.0))
                drift = (jnp.abs(rtn - rn) > _SDC_DRIFT_REL * (rtn + rn)
                         + drift_floor)
                ok = (cont & clean & ~drift) if bp.many else ~drift
                okm = bp.ex(ok)
                # replacement restarts the direction from the true
                # residual, bounding recurrence drift; the passing iterate
                # is promoted to the rollback target xv
                r = jnp.where(okm, rt, r)
                if stencil:
                    p = jnp.where(okm, st_(rt * inv_diag), p)
                    rz = jnp.where(ok, rtn2 * inv_diag, rz)
                else:
                    z = jnp.where(okm, zt, z)
                    p = jnp.where(okm, zt, p)
                    rz = jnp.where(ok, rzt, rz)
                rn = jnp.where(ok, rtn, rn)
                xv = jnp.where(okm, x, xv)
                rrc = rrc + ok.astype(jnp.int32)
                bad = (cont & clean & drift) if bp.many else drift
                det_rr = jnp.where(bad, SDC_DRIFT,
                                   SDC_NONE).astype(jnp.int32)
                return (x, r, z, p, rz, rn, rrc, xv, det_rr)

            def keep(args):
                x, r, z, p, rz, rn, rrc, xv = args
                return (x, r, z, p, rz, rn, rrc, xv,
                        jnp.zeros(jnp.shape(rn), jnp.int32))

            zc = z if carry_z else jnp.zeros((0,), b.dtype)
            x, r, zc, p, rz, rn, rrc, xv, det_rr = lax.cond(
                do_rr, replace, keep,
                (x, r, zc, p, rz, rn, st["rrc"], st["xv"]))
            det = jnp.where(det == SDC_NONE, det_rr, det)
            st2.update(x=x, r=r, p=p, rz=rz, rn=rn, det=det, rrc=rrc,
                       xv=xv)
            if carry_z:
                st2["z"] = zc
        if monitor is not None:
            st2["hist"] = monitor(st2["hist"], it, st2["rn"])
        return st2

    def body(st):
        for _ in range(max(1, int(unroll))):
            st = step(st)
        return st

    st = lax.while_loop(cond, body, st0)
    out = (st["x"], st["it"], st["rn"],
           _reason(st["rn"], tol, atol, st["it"], maxit, st["brk"], dmax),
           st["hist"])
    if g is not None:
        out = out + (st["det"], st["rrc"], st["xv"])
    return out


# ---------------------------------------------------------------------------
# pipelined CG: the 1-reduce-site reduction plan (Ghysels & Vanroose)
# ---------------------------------------------------------------------------


def pipelined_cg_loop(*, b, x0, rtol, atol, maxit, dtol=None,
                      A=None, M=None, pnorm=None, fused=None,
                      guard=None, bp=None, monitor=None, prec=None):
    """Assemble and run the pipelined (single-reduction) CG recurrence.

    Ghysels–Vanroose pipelined CG ("Pipelined, Flexible Krylov Subspace
    Methods", PAPERS.md): every inner product of the iteration —
    ``gamma = <r, u>``, ``delta = <w, u>``, and the monitored
    ``||r||^2`` — is computed from the CURRENT vectors and issued as ONE
    stacked psum (``fused``; the :func:`fuse_psum` seam), while the next
    iteration's operator/PC applies ``m = M w``, ``n = A m`` are
    independent of the reduction results — XLA's async collectives
    overlap the reduce with the SpMV, the latency-hiding the two-stage
    multisplitting line of work gets from restructured communication.
    The extra recurrences (``s = A p``, ``q = M s``, ``z = A M s``) trade
    three more AXPYs for two fewer reduce sites and the overlap.

    The monitored norm lags one iteration (``rr`` is reduced before the
    update it gates), so convergence is detected one body later than
    classic CG — iterates match CG to rounding, iteration counts run one
    higher. The known residual-drift of the u/w recurrences is exactly
    what the guard plan's periodic replacement bounds: the replacement
    recomputes ``r``/``u``/``w`` from the iterate and zeroes the
    direction recurrences (``gamma = 0`` restarts the beta chain).

    ``fused(r, u, w)`` returns ``(gamma, delta, rr)``; guarded,
    ``fused(r, u, w, chk)`` additionally reduces the PREVIOUS body's
    locally-summed ABFT partials (``guard.chk_parts`` — checksum checks
    of that body's fresh ``m = M w``/``n = A m`` applies, carried one
    iteration) in the SAME single psum and returns
    ``(gamma, delta, rr, badA, badM)``.
    """
    bp = bp or SingleBatch()
    g = guard
    st_ = _stc(prec)
    # the scalar recurrences (gamma/alpha) and sgn live in the REDUCE
    # dtype under a mixed plan — fused() returns wide scalars there
    sdt = prec.reduce if (prec is not None and prec.mixed) else b.dtype

    r = b - A(x0)
    if g is not None:
        bnorm, badA0 = g.init(b, r, x0)
    else:
        bnorm = pnorm(b)
    tol = jnp.maximum(rtol * bnorm, atol)
    u = M(r)
    w = A(u)
    rn0 = pnorm(r)
    dmax = _dmax(rn0, dtol)
    hist = _mon0(monitor, rn0, b.dtype)
    sc0 = jnp.zeros(jnp.shape(rn0), sdt)

    # STACKED carries: the state block S = [w, u, r, x] and the direction
    # block V = [z, q, s, p] each update in ONE fused AXPY kernel
    # (S += alpha * sgn * V; V = C + beta * V) instead of eight separate
    # recurrences — on dispatch-bound meshes the kernel count, not the
    # bytes, is the per-iteration floor (measured ~15%/iter on the
    # 8-virtual-device CPU mesh). ``sgn`` encodes the update directions
    # (w/u/r subtract, x adds).
    sgn = jnp.asarray([-1.0, -1.0, -1.0, 1.0],
                      jnp.real(jnp.zeros((), sdt)).dtype
                      ).reshape((4,) + (1,) * b.ndim)
    S0 = jnp.stack([w, u, r, x0])
    st0 = dict(it=_it0(rn0), S=S0, V=jnp.zeros_like(S0),
               gamma=sc0, alpha=sc0, rn=rn0, brk=_false_like(rn0),
               hist=hist)
    if g is not None:
        drift_floor = _SDC_DRIFT_FLOOR_EPS * g.eps * bnorm
        st0.update(det=_det4(badA0, _false_like(rn0), ~jnp.isfinite(rn0),
                             _false_like(rn0)),
                   rrc=_it0(rn0), xv=x0, rnb=rn0,
                   # the init applies' checksum partials, checked by the
                   # FIRST body's stacked psum (one-iteration lag)
                   chk=g.chk_init(r, u, w))
        if bp.many:
            st0["ks"] = jnp.int32(0)

    def active(st):
        live = ((st["rn"] > tol) & (st["rn"] < dmax) & (st["it"] < maxit)
                & ~st["brk"])
        if g is not None:
            live = live & (st["det"] == SDC_NONE)
        return live

    def cond(st):
        return bp.agg(active(st))

    def body(st):
        cont = active(st)
        cm = bp.ex(cont)
        S = st["S"]
        w, u, r = S[0], S[1], S[2]
        if g is not None:                      # the ONE reduce site
            gamma, delta, rr, badA, badM = fused(r, u, w, st["chk"])
        else:
            gamma, delta, rr = fused(r, u, w)
            badA = badM = None
        # overlap work: both applies are independent of the reduction's
        # results, so the collective hides behind them
        m = M(w)
        n = A(m)
        if g is not None:
            # this body's fresh-apply checksum partials, reduced by the
            # NEXT body's stacked psum (w here is the pre-update M input)
            chk_new = g.chk_parts(m, n, w)
        # gamma==0 marks both the first iteration and a post-replacement
        # restart (the guard zeroes the carry): the beta chain starts fresh
        first = st["gamma"] == 0
        gold = jnp.where(first, 1.0, st["gamma"])
        beta = jnp.where(first, 0.0, gamma / gold)
        aold = jnp.where(st["alpha"] == 0, 1.0, st["alpha"])
        denom = jnp.where(first, delta, delta - beta * gamma / aold)
        brk_new = cont & (denom == 0)
        alpha = jnp.where(denom == 0, 0.0,
                          gamma / jnp.where(denom == 0, 1.0, denom))
        be, al = bp.ex(beta), bp.ex(alpha)
        # V = [z, q, s, p] <- [n, m, w, u] + beta V ; then the state rows
        # [w, u, r, x] -= / += alpha * V rows — two fused kernels total
        V = jnp.where(cm, st_(jnp.stack([n, m, w, u]) + be * st["V"]),
                      st["V"])
        S = jnp.where(cm, st_(S + al * (sgn * V)), S)
        # rr = <r, r> is real by construction; take the real part so the
        # carried norm stays real-typed for complex operators
        rn_new = jnp.sqrt(jnp.maximum(jnp.real(rr), 0.0))
        rn = jnp.where(cont, rn_new, st["rn"])
        gamma_c = jnp.where(cont, gamma, st["gamma"])
        alpha_c = jnp.where(cont, alpha, st["alpha"])
        it = st["it"] + cont.astype(jnp.int32)

        st2 = dict(it=it, S=S, V=V, gamma=gamma_c, alpha=alpha_c, rn=rn,
                   brk=st["brk"] | brk_new, hist=st["hist"])

        if g is not None:
            if bp.many:
                badnan = cont & ~jnp.isfinite(rn)
                badmono = cont & jnp.isfinite(rn) & (rn > _SDC_MONO_FACTOR
                                                     * st["rnb"])
                rnb = jnp.where(cont & jnp.isfinite(rn),
                                jnp.minimum(st["rnb"], rn), st["rnb"])
                det = jnp.where(st["det"] == SDC_NONE,
                                _det4(cont & badA, cont & badM, badnan,
                                      badmono),
                                st["det"])
                ks = st["ks"] + 1
                clean = det == SDC_NONE
                do_rr = (jnp.any(cont & clean) & (g.rr_n > 0)
                         & (ks % jnp.maximum(g.rr_n, 1) == 0))
                st2["ks"] = ks
            else:
                badnan = ~jnp.isfinite(rn)
                badmono = jnp.isfinite(rn) & (rn > _SDC_MONO_FACTOR
                                              * st["rnb"])
                rnb = jnp.where(jnp.isfinite(rn),
                                jnp.minimum(st["rnb"], rn), st["rnb"])
                det = _det4(badA, badM, badnan, badmono)
                clean = det == SDC_NONE
                do_rr = ((det == SDC_NONE) & (g.rr_n > 0)
                         & (it % jnp.maximum(g.rr_n, 1) == 0) & (rn > tol))
            st2["rnb"] = rnb

            def replace(args):
                S, V, gamma_c, alpha_c, rn, rrc, xv = args
                x = S[3]
                # full pipeline refill from the TRUE residual: the u/w
                # recurrences (the pipelined drift source) are recomputed
                # from scratch, the direction recurrences restart
                rt = b - A(x)
                ut = M(rt)
                wt = A(ut)
                # plain-psum verifier; the drift gate compares against the
                # CURRENT recurrence residual (the carried norm lags one
                # iteration — see _make_pipe_guard.vpair2)
                rtn2, rc2 = g.vpair2(rt, S[2])
                rtn = jnp.sqrt(jnp.maximum(rtn2, 0.0))
                rcur = jnp.sqrt(jnp.maximum(rc2, 0.0))
                drift = (jnp.abs(rtn - rcur)
                         > _SDC_DRIFT_REL * (rtn + rcur) + drift_floor)
                ok = (cont & clean & ~drift) if bp.many else ~drift
                okm = bp.ex(ok)
                S = jnp.where(okm, jnp.stack([wt, ut, rt, x]), S)
                V = jnp.where(okm, 0.0, V)
                gamma_c = jnp.where(ok, 0.0, gamma_c)  # fresh beta chain
                alpha_c = jnp.where(ok, 0.0, alpha_c)
                rn = jnp.where(ok, rtn, rn)
                xv = jnp.where(okm, x, xv)
                rrc = rrc + ok.astype(jnp.int32)
                bad = (cont & clean & drift) if bp.many else drift
                det_rr = jnp.where(bad, SDC_DRIFT,
                                   SDC_NONE).astype(jnp.int32)
                return (S, V, gamma_c, alpha_c, rn, rrc, xv, det_rr)

            def keep(args):
                return args + (jnp.zeros(jnp.shape(args[4]), jnp.int32),)

            (S, V, gamma_c, alpha_c, rn, rrc, xv, det_rr) = lax.cond(
                do_rr, replace, keep,
                (S, V, gamma_c, alpha_c, rn, st["rrc"], st["xv"]))
            det = jnp.where(det == SDC_NONE, det_rr, det)
            st2.update(S=S, V=V, gamma=gamma_c, alpha=alpha_c, rn=rn,
                       det=det, rrc=rrc, xv=xv, chk=chk_new)
        if monitor is not None:
            st2["hist"] = monitor(st2["hist"], it, st2["rn"])
        return st2

    st = lax.while_loop(cond, body, st0)
    xf = st["S"][3]
    # the monitored norm lags one iteration; report the exact final
    # residual (plain psum — the verifier channel, outside the loop) while
    # judging the reason on the norm the loop actually tested
    if g is not None:
        rn_true = jnp.sqrt(jnp.maximum(g.vnorm2(b - A(xf)), 0.0))
    else:
        rn_true = pnorm(b - A(xf))
    out = (xf, st["it"], rn_true,
           _reason(st["rn"], tol, atol, st["it"], maxit, st["brk"], dmax),
           st["hist"])
    if g is not None:
        out = out + (st["det"], st["rrc"], st["xv"])
    return out


# ---------------------------------------------------------------------------
# s-step communication-avoiding CG: ONE reduce site per s iterations
# ---------------------------------------------------------------------------


def _sstep_shift(s: int, m: int) -> np.ndarray:
    """The coordinate shift of ``(MA)`` over the two monomial sub-bases:
    column ``i`` of the p-chain maps to ``i+1`` (i < s), column ``i`` of
    the z-chain likewise (i < s-1); the last column of each chain has no
    image in the basis and by the degree bookkeeping of
    :func:`sstep_cg_loop` never carries a coefficient when shifted."""
    S = np.zeros((m, m))
    for i in range(s):
        S[i + 1, i] = 1.0
    for i in range(s - 1):
        S[s + 2 + i, s + 1 + i] = 1.0
    return S


def sstep_cg_loop(*, b, x0, rtol, atol, maxit, s, greduce,
                  A=None, M=None, pnorm=None, dtol=None,
                  guard=None, bp=None, monitor=None, prec=None,
                  max_repl=None):
    """Assemble and run the s-step (communication-avoiding) CG recurrence.

    Each ``lax.while_loop`` body advances CG by **s iterations** around a
    SINGLE stacked psum — the tall-skinny Gram matrix of the block's
    monomial Krylov bases (the CA-CG of Chronopoulos–Gear / Carson; the
    amortization the "two-stage multisplitting" scale-out tier wants on
    interconnects where even one reduction per iteration dominates):

    * **basis build** — from the carried ``(p, r)``, the two preconditioned
      monomial chains ``P̃ = [p, (MA)p, …, (MA)^s p]`` (s+1 columns) and
      ``R̃ = [z, (MA)z, …, (MA)^{s-1} z]`` with ``z = M r`` (s columns):
      ``2s-1`` operator applies + ``2s`` PC applies of LOCAL work and
      halo/gather traffic, ZERO reductions. The A-images ``W = A·[P̃, R̃]``
      are the chain intermediates — no extra applies.
    * **the ONE reduce site** — the Gram matrix of ``C = [V_Z, W, r]``
      (``V_Z = [P̃, R̃]``, m = 2s+1 columns): one ``(2m+1)²`` stacked psum
      (:func:`fuse_gram_psum`, the MXU-friendly tall-skinny matmul)
      carrying every inner product the s iterations need — ``⟨p,Ap⟩``,
      ``⟨r,z⟩``, ``‖r‖²`` — plus, guarded, the ABFT checksum partials of
      every basis-build apply in the SAME stack.
    * **coefficient recurrences** — the s CG iterations advance as
      HOST-FREE small-vector recurrences in basis coordinates
      (``p̂``, ``ẑ``, and the shared update vector ``ĉ`` with
      ``x_j = x_0 + V_Z ĉ_j``, ``r_j = r_0 - W ĉ_j``), statically
      unrolled inside the same body; per-step masked freezing gives exact
      classic-CG iteration counts and per-column convergence under the
      batching plan.
    * **block end** — three basis combinations materialize
      ``(x, r, p)`` for the next block (or exit).

    The known CA-CG instability — the monomial basis' conditioning grows
    like ``κ^{s/2}``, so coordinate inner products lose accuracy at large
    ``s`` — is handled by the guard plan's residual-replacement gate: on
    drift the TRUE residual restarts the recurrence (the next block
    rebuilds the basis from it), and past ``max_repl`` restarts
    (``-ksp_sstep_max_replacements``) the loop exits with the
    ``SDC_DEMOTE`` code so the host demotes the solve to classic CG.

    ``greduce(parts)`` is the builder-supplied fused reduction (the
    :func:`fuse_gram_psum` seam routed through the injectable psum);
    ``pnorm`` serves init/epilogue only — the loop body performs NO other
    collective. Output contract matches :func:`pipelined_cg_loop`
    (``rn`` reported as the exact final residual, reason judged on the
    recurrence norm; guarded: ``(…, det, rrc, xv)``).
    """
    bp = bp or SingleBatch()
    many = bp.many
    g = guard
    st_ = _stc(prec)
    up = (prec.up if prec is not None and prec.mixed else (lambda v: v))
    s = int(s)
    if s < 1:
        raise ValueError(f"-ksp_sstep_s must be >= 1, got {s}")
    m = 2 * s + 1
    cdt = (prec.reduce if prec is not None and prec.mixed else b.dtype)
    rdt = jnp.real(jnp.zeros((), cdt)).dtype
    Sm = jnp.asarray(_sstep_shift(s, m), rdt)
    # W columns with a valid A-image (the chain intermediates): the last
    # column of each sub-basis has none and is carried as zeros
    w_valid = np.zeros((m,), bool)
    w_valid[0:s] = True
    w_valid[s + 1:2 * s] = True
    tail = (b.shape[1],) if many else ()

    # ---- init --------------------------------------------------------------
    r = b - A(x0)
    if g is not None:
        bnorm, badA0 = g.init(b, r, x0)
    else:
        bnorm = pnorm(b)
    tol = jnp.maximum(rtol * bnorm, atol)
    rn0 = pnorm(r)
    p = M(r)                       # classic CG init direction p_0 = z_0
    dmax = _dmax(rn0, dtol)
    hist = _mon0(monitor, rn0, b.dtype)

    st0 = dict(it=_it0(rn0), x=x0, r=r, p=p, rn=rn0, brk=_false_like(rn0),
               hist=hist)
    if g is not None:
        st0.update(det=_det4(badA0, _false_like(rn0), ~jnp.isfinite(rn0),
                             _false_like(rn0)),
                   rrc=_it0(rn0), xv=x0, rnb=rn0, drc=_it0(rn0),
                   rn_rr=rn0, ks=jnp.int32(0))

    def active(st):
        live = ((st["rn"] > tol) & (st["rn"] < dmax) & (st["it"] < maxit)
                & ~st["brk"])
        if g is not None:
            live = live & (st["det"] == SDC_NONE)
        return live

    def cond(st):
        return bp.agg(active(st))

    # ---- coordinate helpers (shapes (m[,k]) / (m,m[,k])) -------------------
    def cmat(Gm, v):
        return jnp.einsum("ab...,b...->a...", Gm, v)

    def cdot(u, v):
        return jnp.sum(jnp.conj(u) * v, axis=0)

    def combine(basis, coef):
        c = coef[:, None, :] if many else coef[:, None]
        return jnp.sum(basis * c, axis=0)

    def colsum(Bst):
        return jnp.sum(up(Bst), axis=1)

    def colasum(Bst):
        return jnp.sum(jnp.abs(up(Bst)), axis=1)

    def cmul_basis(c, Bst):
        cc = up(c)
        cc = cc[None, :, None] if many else cc[None, :]
        return cc * up(Bst)

    def onehot(idx):
        return jnp.zeros((m,) + tail, cdt).at[idx].set(1.0)

    def body(st):
        cont = active(st)
        cm = bp.ex(cont)
        x, r, p = st["x"], st["r"], st["p"]

        # ---- basis build: 2s-1 A applies + 2s M applies, NO reductions ----
        Pcols = [p]
        Wp = []
        for _ in range(s):
            t = A(Pcols[-1])
            Wp.append(t)
            Pcols.append(st_(M(t)))
        z = st_(M(r))
        Rcols = [z]
        Wr = []
        for _ in range(s - 1):
            u = A(Rcols[-1])
            Wr.append(u)
            Rcols.append(st_(M(u)))
        zero = jnp.zeros_like(b)
        Bz = jnp.stack(Pcols[:s + 1] + Rcols)          # V_Z (m, …)
        Bw = jnp.stack(Wp + [zero] + Wr + [zero])      # A·V_Z (valid cols)

        # ---- the ONE reduce site: Gram + folded guard partials ----
        Cup = up(jnp.concatenate([Bz, Bw, r[None]], axis=0))
        if many:
            E_local = jnp.einsum("aLk,bLk->abk", jnp.conj(Cup), Cup)
        else:
            E_local = jnp.einsum("aL,bL->ab", jnp.conj(Cup), Cup)
        parts = [E_local]
        if g is not None and g.cs is not None:
            CsB = cmul_basis(g.cs, Bz)
            parts += [colsum(Bw), colsum(CsB), colasum(Bw), colasum(CsB)]
        if g is not None and g.csM is not None:
            CmW = cmul_basis(g.csM, Bw)
            cr_ = up(g.csM)[:, None] * up(r) if many else up(g.csM) * up(r)
            parts += [colsum(Bz), colsum(CmW), colasum(Bz), colasum(CmW),
                      jnp.sum(cr_, axis=0), jnp.sum(jnp.abs(cr_), axis=0)]
        outs = greduce(parts)
        E = outs[0]
        i_out = 1
        badA = badM = None
        if g is not None:
            thr = lambda scale: g.abft_tol * g.eps * scale
            vm = jnp.asarray(w_valid[:, None] if many else w_valid)
            if g.cs is not None:
                sW, cV, aW, aCV = outs[i_out:i_out + 4]
                i_out += 4
                badA = jnp.any((jnp.abs(sW - cV)
                                > thr(jnp.real(aW) + jnp.real(aCV))) & vm,
                               axis=0)
            else:
                badA = g.no_bad(r)
            if g.csM is not None:
                sV, cW, aV, aCW, cr, acr = outs[i_out:i_out + 6]
                i_out += 6
                # expected column sums of V_Z under the PC checksum: each
                # column is an M apply of (W column | r) — map inputs to
                # outputs positionally; column 0 (the carried p) has no
                # in-block apply and checks against itself (diff 0)
                exp = jnp.concatenate(
                    [sV[0:1], cW[0:s], cr[None], cW[s + 1:2 * s]], axis=0)
                aexp = jnp.concatenate(
                    [aV[0:1], aCW[0:s], acr[None], aCW[s + 1:2 * s]],
                    axis=0)
                badM = jnp.any(jnp.abs(sV - exp)
                               > thr(jnp.real(aV) + jnp.real(aexp)),
                               axis=0)
            else:
                badM = g.no_bad(r)

        # Gram blocks: G1 = ⟨V_Z, W⟩, G2 = ⟨W, W⟩, g0 = ⟨V_Z, r⟩,
        # w0 = ⟨W, r⟩, rr0 = ‖r‖²
        G1 = E[0:m, m:2 * m]
        G2 = E[m:2 * m, m:2 * m]
        g0 = E[0:m, 2 * m]
        w0 = E[m:2 * m, 2 * m]
        rr0 = jnp.real(E[2 * m, 2 * m])
        G1H = jnp.conj(jnp.swapaxes(G1, 0, 1))

        def rz_of(zh, ch):
            return cdot(g0, zh) - cdot(ch, cmat(G1H, zh))

        # ---- s CG iterations as host-free coordinate recurrences ----
        phat = onehot(0)
        zhat = onehot(s + 1)
        chat = jnp.zeros((m,) + tail, cdt)
        rz = rz_of(zhat, chat)
        it, brk, hist = st["it"], st["brk"], st["hist"]
        # block-start norm REFRESH: rr0 is psummed directly (not a
        # difference), so this heals any resolution noise the previous
        # block's coordinate norms carried — and is what the guard's
        # monotonicity sentinel watches (coordinate norms at stalled
        # basis conditioning are noise; flagging them would turn the
        # CA-CG stability artifact into a false corruption verdict)
        rn_bs = jnp.where(cont, jnp.sqrt(jnp.maximum(rr0, 0.0)),
                          st["rn"])
        rn = rn_bs
        # in-block resolution floor (see _SSTEP_RR_FLOOR): below it the
        # coordinate residual is rounding noise — clamp the reported
        # norm at the floor (never fake convergence on noise) and freeze
        # the block; the next block restarts at full precision
        eps_r = jnp.finfo(rdt).eps
        rr_floor = _SSTEP_RR_FLOOR * m * eps_r * jnp.maximum(rr0, 0.0)
        rn_floor = jnp.sqrt(rr_floor)
        a = cont & (rn > tol)
        for _ in range(s):
            pAp = cdot(phat, cmat(G1, phat))
            brk_j = a & (pAp == 0)
            brk = brk | brk_j
            a = a & ~brk_j
            alpha = jnp.where(pAp == 0, 0.0,
                              rz / jnp.where(pAp == 0, 1.0, pAp))
            chat = jnp.where(a, chat + alpha * phat, chat)
            zhat = jnp.where(a, zhat - alpha * cmat(Sm, phat), zhat)
            rz_new = rz_of(zhat, chat)
            rr_new = (rr0 - 2.0 * jnp.real(cdot(chat, w0))
                      + jnp.real(cdot(chat, cmat(G2, chat))))
            floor_hit = rr_new <= rr_floor
            rn_new = jnp.maximum(jnp.sqrt(jnp.maximum(rr_new, 0.0)),
                                 rn_floor)
            beta = jnp.where(rz == 0, 0.0,
                             rz_new / jnp.where(rz == 0, 1.0, rz))
            phat = jnp.where(a, zhat + beta * phat, phat)
            rz = jnp.where(a, rz_new, rz)
            rn = jnp.where(a, rn_new, rn)
            it = it + a.astype(jnp.int32)
            if monitor is not None:
                hist = monitor(hist, it, rn)
            a = (a & ~floor_hit & (rn > tol) & (rn < dmax)
                 & (it < maxit))

        # ---- block end: materialize (x, r, p) from coordinates ----
        x_new = jnp.where(cm, st_(x + combine(Bz, chat)), x)
        r_new = jnp.where(cm, st_(r - combine(Bw, chat)), r)
        p_new = jnp.where(cm, st_(combine(Bz, phat)), p)
        st2 = dict(it=it, x=x_new, r=r_new, p=p_new, rn=rn, brk=brk,
                   hist=hist)

        if g is not None:
            # sentinels run on the EXACT block-start norm (one-block
            # detection lag; the ABFT channel catches apply corruption
            # immediately) — in-block coordinate norms are excluded on
            # purpose, see the rn_bs comment above. With the
            # replacement gate armed, a NaN/blow-up anomaly is the
            # CA-CG instability signature (a garbage coordinate step at
            # stalled basis conditioning can explode the iterate): it
            # ROLLS BACK to the verified carry in-program and counts
            # against the demotion budget, instead of raising a false
            # corruption verdict the host would deterministically
            # re-trip. Without the gate (abft-only), the sentinels keep
            # the classic det-code semantics.
            badnan = cont & ~jnp.isfinite(rn_bs)
            badmono = cont & jnp.isfinite(rn_bs) & (rn_bs
                                                    > _SDC_MONO_FACTOR
                                                    * st["rnb"])
            rnb = jnp.where(cont & jnp.isfinite(rn_bs),
                            jnp.minimum(st["rnb"], rn_bs), st["rnb"])
            gated = g.rr_n > 0
            det = jnp.where(st["det"] == SDC_NONE,
                            _det4(cont & badA, cont & badM,
                                  badnan & ~gated, badmono & ~gated),
                            st["det"])
            ks = st["ks"] + 1
            clean = det == SDC_NONE
            anomaly = (badnan | badmono) & gated & clean
            # the replacement interval is in ITERATIONS (-ksp_residual_
            # replacement N); an s-block covers s of them
            interval = jnp.maximum((g.rr_n + s - 1) // s, 1)
            do_rr = ((bp.agg(cont & clean) & gated
                      & (ks % interval == 0))
                     | bp.agg(anomaly))
            st2["rnb"] = rnb
            st2["ks"] = ks

            def replace(args):
                x_, r_, p_, rn_, rrc, xv, drc, rn_rr = args
                # an anomalous iterate resumes from the VERIFIED carry;
                # TRUE residual + fresh direction either way, norms on
                # plain psum (the verifier channel — a corrupted
                # verifier would lie)
                xr = jnp.where(bp.ex(anomaly), xv, x_)
                rt = b - A(xr)
                zt = M(rt)
                rtn2, _rzt = g.vpair(rt, zt)
                rtn = jnp.sqrt(jnp.maximum(rtn2, 0.0))
                # CA-CG stability gate: basis ill-conditioning shows as
                # STAGNATION of the true residual between checks (see
                # _SSTEP_STALL_FACTOR) or as the anomaly above — on
                # either, restart the recurrence from the true residual
                # (the next block rebuilds the basis), and past the
                # max_repl budget demote to classic CG (SDC_DEMOTE)
                stall = (anomaly
                         | ((rtn > tol)
                            & (rtn >= _SSTEP_STALL_FACTOR * rn_rr)))
                base = cont & clean
                ok = base & ~stall
                restart = base & stall & (drc < max_repl)
                demote = base & stall & (drc >= max_repl)
                take = bp.ex(ok | restart)
                x2_ = jnp.where(bp.ex(anomaly), xv, x_)
                r2 = jnp.where(take, st_(rt), r_)
                p2 = jnp.where(take, st_(zt), p_)
                rn2 = jnp.where(ok | restart | demote, rtn, rn_)
                xv2 = jnp.where(bp.ex(ok), x_, xv)
                rrc2 = rrc + ok.astype(jnp.int32)
                drc2 = drc + restart.astype(jnp.int32)
                rn_rr2 = jnp.where(ok | restart, rtn, rn_rr)
                det_rr = jnp.where(demote, SDC_DEMOTE,
                                   SDC_NONE).astype(jnp.int32)
                return (x2_, r2, p2, rn2, rrc2, xv2, drc2, rn_rr2,
                        det_rr)

            def keep(args):
                return args + (jnp.zeros(jnp.shape(args[3]), jnp.int32),)

            (x2, r2, p2, rn2, rrc, xv, drc, rn_rr, det_rr) = lax.cond(
                do_rr, replace, keep,
                (x_new, r_new, p_new, rn, st["rrc"], st["xv"],
                 st["drc"], st["rn_rr"]))
            det = jnp.where(det == SDC_NONE, det_rr, det)
            st2.update(x=x2, r=r2, p=p2, rn=rn2, det=det, rrc=rrc,
                       xv=xv, drc=drc, rn_rr=rn_rr)
        return st2

    st = lax.while_loop(cond, body, st0)
    xf = st["x"]
    # coordinate norms drift with the basis conditioning; report the exact
    # final residual (the pipelined plan's epilogue discipline) while
    # judging the reason on the norm the loop actually tested
    if g is not None:
        rn_true = jnp.sqrt(jnp.maximum(g.vnorm2(b - A(xf)), 0.0))
    else:
        rn_true = pnorm(b - A(xf))
    out = (xf, st["it"], rn_true,
           _reason(st["rn"], tol, atol, st["it"], maxit, st["brk"], dmax),
           st["hist"])
    if g is not None:
        out = out + (st["det"], st["rrc"], st["xv"])
    return out
