"""Measured-latency reduction-plan auto-selection (``-ksp_reduction_auto``).

The repo now ships THREE reduction plans for the CG family — classic
(3 psum sites/iteration), pipelined (1 site, overlapped), and s-step
(1 site per s iterations at ~2x the operator applies) — and which one is
fastest is a property of the MESH, not the operator: on a single-host
CPU mesh a psum is a ~µs thread rendezvous and classic CG wins; through
a ~100 µs-per-reduction interconnect the 1-site plans win by the latency
they stop paying ("A highly scalable approach to solving linear systems
using two-stage multisplitting" frames exactly this ranking-by-
communication-cost). This module measures instead of guessing:

* :func:`measure_psum_latency_us` — the chained-psum probe (one program
  running N dependent scalar psums): the per-reduce-site latency each
  removed site buys back. Shared with
  ``benchmarks/multichip_weak_scaling.py`` so the bench and the selector
  price latency with ONE definition.
* :func:`probe_psum_latency_us` — the same probe behind an on-disk cache
  keyed by ``host_machine_fingerprint()`` + mesh topology (the utils/aot
  discipline: atomic writes, silent fallback), so auto-select does not
  re-pay the probe per process; ``-ksp_reduction_probe_refresh`` kills
  the cache.
* :func:`measure_apply_latency_us` — a chained operator+PC apply program
  timing one A+M application (halo traffic included) on the actual
  operands.
* :func:`select_reduction_plan` — ranks {cg, pipecg, sstep s∈{2,4,8}}
  under the additive model ``cost = applies·apply_us + sites·psum_us``
  and returns the winner with the full ranking attached. The model is
  deliberately conservative: it omits the per-plan bookkeeping overhead
  (pipecg's extra AXPY recurrences, sstep's Gram/combine arithmetic —
  measured at 10-20% of an iteration on the CPU mesh), so a plan must
  beat classic CG by ``margin`` (default 25% of the modeled cost) to
  displace it — on low-latency meshes auto-select therefore honestly
  keeps classic CG.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from dataclasses import dataclass, field

import numpy as np

#: candidate reduction plans: ("cg", None), ("pipecg", None), ("sstep", s)
DEFAULT_CANDIDATES = (("cg", None), ("pipecg", None),
                      ("sstep", 2), ("sstep", 4), ("sstep", 8))

#: modeled applies/iteration and psum sites/iteration per plan family.
#: cg: the general 3-phase schedule; pipecg: one fused site (overlap not
#: credited); sstep: the two-basis monomial CA-CG's (2s-1)/s applies and
#: 1/s sites. The constants mirror KSP._REDUCE_SITES / the collective-
#: volume gates — pinned against them in tests/test_sstep.py.
def _plan_model(ksp_type: str, s):
    if ksp_type == "cg":
        return 1.0, 3.0
    if ksp_type == "pipecg":
        return 1.0, 1.0
    if ksp_type == "sstep":
        s = int(s)
        return (2.0 * s - 1.0) / s, 1.0 / s
    raise ValueError(f"no reduction-plan model for KSP {ksp_type!r}")


def measure_psum_latency_us(comm, chain: int = 256) -> float:
    """Measured per-reduce-site latency of the mesh: one program running
    ``chain`` DEPENDENT scalar psums (each divides by the mesh size, so
    the value is preserved and the chain cannot be collapsed), timed
    best-of-3. This is the latency each removed reduce site saves per
    iteration — the quantity the 1-site reduction plans are buying back.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axis = comm.axis
    ndev = comm.size

    def local(v):
        sm = jnp.sum(v)

        def body(_i, a):
            return lax.psum(a, axis) / ndev

        return lax.fori_loop(0, chain, body, sm)

    prog = jax.jit(comm.shard_map(local, (P(axis),), P()))
    v = comm.put_rows(np.ones(8 * ndev))
    jax.block_until_ready(prog(v))          # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(prog(v))
        best = min(best, time.perf_counter() - t0)
    return best / chain * 1e6


def _probe_dir() -> str:
    from ..utils import aot
    return os.path.join(os.path.dirname(aot.cache_dir()), "probe")


def _probe_path(comm) -> str:
    from ..utils import aot
    d0 = comm.devices[0]
    payload = repr((aot.host_machine_fingerprint(), len(comm.devices),
                    d0.platform, getattr(d0, "device_kind", ""),
                    comm.axis))
    digest = hashlib.sha256(payload.encode()).hexdigest()[:24]
    return os.path.join(_probe_dir(), f"psum_{digest}.json")


def probe_psum_latency_us(comm, chain: int = 256,
                          refresh: bool = False) -> tuple:
    """The psum-latency probe behind the on-disk cache: returns
    ``(psum_us, cached)``. Cache key = host machine fingerprint + mesh
    topology (a different machine or mesh shape simply misses); writes
    are atomic (tmp + ``os.replace``), every read/write failure degrades
    silently to a fresh measurement; ``refresh`` re-measures and
    overwrites (the ``-ksp_reduction_probe_refresh`` kill switch)."""
    path = _probe_path(comm)
    if not refresh:
        try:
            with open(path, encoding="utf-8") as fh:
                blob = json.load(fh)
            if blob.get("chain") == int(chain):
                return float(blob["psum_us"]), True
        # tpslint: disable=TPS005 — best-effort cache read: a corrupt or
        # stale blob must fall back to measuring, whatever it raises
        except Exception:
            pass
    psum_us = measure_psum_latency_us(comm, chain=chain)
    try:
        os.makedirs(_probe_dir(), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=_probe_dir(), suffix=".tmp")
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump({"psum_us": psum_us, "chain": int(chain),
                       "devices": int(comm.size)}, fh)
        os.replace(tmp, path)       # atomic publish (checkpoint.py rule)
    except OSError:
        pass
    return psum_us, False


def measure_apply_latency_us(comm, operator, pc, chain: int = 16) -> float:
    """Measured wall of ONE operator+PC application (halo/gather traffic
    included) on the actual operands: a chained-apply program (each
    iterate scaled by 0.5 so magnitudes stay bounded), best-of-3.
    Per-operator, deliberately NOT disk-cached — apply cost depends on
    the operand geometry, unlike the mesh's psum latency."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axis = comm.axis
    n = operator.shape[0]
    pc.set_up(pc._mat or operator)      # idempotent (keyed on mat state)
    spmv = operator.local_spmv(comm)
    pc_apply = pc.local_apply(comm, n)

    def local(op_arrays, pc_arrays, v):
        def body(_i, u):
            return pc_apply(pc_arrays, spmv(op_arrays, u)) * 0.5

        return lax.fori_loop(0, chain, body, v)

    prog = jax.jit(comm.shard_map(
        local, (operator.op_specs(axis), pc.in_specs(axis), P(axis)),
        P(axis)))
    v = comm.put_rows(np.ones(n, dtype=np.dtype(operator.dtype)))
    jax.block_until_ready(prog(operator.device_arrays(),
                               pc.device_arrays(), v))   # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(prog(operator.device_arrays(),
                                   pc.device_arrays(), v))
        best = min(best, time.perf_counter() - t0)
    return best / chain * 1e6


def rank_reduction_plans(psum_us: float, apply_us: float,
                         candidates=DEFAULT_CANDIDATES) -> list:
    """Rank the candidate plans under the additive per-iteration model
    ``cost_us = applies·apply_us + sites·psum_us`` — cheapest first.
    Returns one dict per candidate with the model inputs spelled out so
    benches/reports can publish the ranking verbatim."""
    ranked = []
    for ksp_type, s in candidates:
        applies, sites = _plan_model(ksp_type, s)
        ranked.append({
            "ksp_type": ksp_type, "s": int(s) if s else 0,
            "applies_per_iter": applies, "sites_per_iter": sites,
            "model_cost_us": applies * apply_us + sites * psum_us,
        })
    ranked.sort(key=lambda r: r["model_cost_us"])
    return ranked


@dataclass
class SelectionReport:
    """What :func:`select_reduction_plan` decided and WHY — published
    verbatim by cfg15 and the weak-scaling bench (the honesty contract:
    on the CPU mesh the measured psum latency is ~µs and the report says
    classic CG keeps winning)."""
    ksp_type: str
    s: int
    psum_us: float
    apply_us: float
    probe_cached: bool
    margin: float
    model: str = "additive: applies*apply_us + sites*psum_us"
    ranking: list = field(default_factory=list)

    def as_dict(self) -> dict:
        return {"choice": self.ksp_type, "s": self.s,
                "psum_us": self.psum_us, "apply_us": self.apply_us,
                "probe_cached": self.probe_cached, "margin": self.margin,
                "model": self.model, "ranking": self.ranking}


def select_reduction_plan(comm, operator, pc, *,
                          candidates=DEFAULT_CANDIDATES,
                          refresh: bool = False,
                          margin: float = 0.25) -> SelectionReport:
    """Pick the reduction plan for (mesh, operator, pc) from MEASURED
    latencies. A non-classic plan must beat classic CG's modeled cost by
    ``margin`` (fractional) to displace it: the additive model omits the
    per-plan bookkeeping overhead (pipecg's extra recurrences, sstep's
    Gram arithmetic), so marginal modeled wins on low-latency meshes are
    noise — classic CG is kept and the report says why."""
    from ..telemetry.metrics import registry
    psum_us, cached = probe_psum_latency_us(comm, refresh=refresh)
    registry.gauge("autoselect.psum_latency_us").set(psum_us)
    apply_us = measure_apply_latency_us(comm, operator, pc)
    ranking = rank_reduction_plans(psum_us, apply_us, candidates)
    cg_cost = next(r["model_cost_us"] for r in ranking
                   if r["ksp_type"] == "cg")
    best = ranking[0]
    if (best["ksp_type"] != "cg"
            and best["model_cost_us"] > (1.0 - margin) * cg_cost):
        best = {"ksp_type": "cg", "s": 0}
    return SelectionReport(ksp_type=best["ksp_type"],
                           s=int(best.get("s", 0) or 0),
                           psum_us=float(psum_us),
                           apply_us=float(apply_us),
                           probe_cached=bool(cached), margin=margin,
                           ranking=ranking)
