"""Elastic degraded-mesh recovery: rebuild a solve on fewer devices.

The PR-2/PR-5 resilience machinery recovers onto the SAME mesh geometry:
a transient ``unavailable`` fault is retried in place after a backoff.
A PERSISTENTLY lost device breaks that model — every same-mesh retry
fails identically, ``resilient_solve`` backs off until its policy is
exhausted, and a serving session dies with its hardware. This module is
the escalation tier past same-mesh retries:

* :class:`ElasticPolicy` — when to give up on the current mesh
  (``-elastic_max_same_mesh_retries``), how far down the ladder to go
  (``-elastic_min_devices``), and whether UNattributed persistent
  failures may trigger a speculative shrink
  (``-elastic_shrink_unattributed``, default off: without a device to
  exclude, halving the mesh is a guess — with a real lost device the
  next shrink excludes more until the bad device is out or the floor is
  hit).
* :class:`MeshRebuilder` — plans the largest viable STRICTLY SMALLER
  mesh from surviving devices (8 -> 4 -> 2 -> 1 on the default
  power-of-two ladder, which keeps the compiled-program population
  bounded exactly like the serving layer's pad_pow2 policy) and
  rebuilds operators / PC factors / solver sessions on it. Since the
  fleet round the ladder also goes UP: :meth:`MeshRebuilder.grown_comm`
  plans the largest viable strictly LARGER mesh over healed devices
  (never past the mesh the caller originally provisioned) once a
  :class:`~.faults.HealthMonitor` observes :func:`~.faults.heal` — a
  repaired device is a capacity event, not permanent degradation.
* helpers shared by retry.py's ``mesh_shrink`` escalation stage and the
  SolveServer's shrink adoption: :func:`rebuild_operator` (re-place the
  operand arrays on the new mesh — CSR matrices round-trip through
  their host CSR; matrix-free operators expose ``with_comm``),
  :func:`rebuild_ksp` (fresh PC of the same type and tunables, factors
  re-set-up on the new geometry; the ABFT checksum placement re-keys
  automatically on the new operator identity), :func:`rebind_vec`
  (re-point a caller's Vec at new-mesh storage in place, so the vectors
  a driver holds stay valid across the shrink), and :func:`warm`
  (pre-build — compile or AOT-load — the new geometry's programs by
  dispatching zero-RHS solves that converge at iteration 0).

The state that moves across the shrink is the last CHECKPOINTED (or
in-memory partial) iterate, resharded through the already-elastic
checkpoint format (utils/checkpoint.py round-trips any mesh size): the
resumed solve continues from the verified iteration, never from zero.

PARITY.md "Elastic recovery": PETSc-on-MPI has no analog — a rank loss
aborts the communicator (MPI ULFM, the closest standard, still requires
the application to rebuild everything by hand). This is a deliberate
divergence the checkpoint layer was designed for.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..utils.options import global_options
from . import faults as _faults


@dataclass
class ElasticPolicy:
    """When and how far to escalate from same-mesh retries to a shrink.

    ``enabled``
        Master switch (``-elastic_enable``). On by default: the shrink
        stage only ever engages after the HealthMonitor classifies the
        failure pattern as persistent, so transient-fault behavior is
        byte-identical with or without it.
    ``max_same_mesh_retries``
        Unavailable failures on one mesh before the escalation
        (``-elastic_max_same_mesh_retries``) — also the
        :class:`~.faults.HealthMonitor` classification threshold.
    ``min_devices``
        The smallest mesh the ladder may land on
        (``-elastic_min_devices``); below it the original error
        re-raises (nothing left to degrade to).
    ``shrink_unattributed``
        Allow a speculative halving when the repeated failures name no
        device (``-elastic_shrink_unattributed``, default off — see the
        module docstring).
    ``regrow``
        Arm the ladder's UPWARD direction (``-elastic_regrow``, default
        on): once :func:`~.faults.heal` clears a lost device, a session
        that previously shrank may be rebuilt onto the larger mesh —
        never past the mesh the caller originally built it on.
    ``prefer_pow2``
        Land on power-of-two mesh sizes (the bounded-program-population
        ladder); False uses every surviving device.
    """
    enabled: bool = True
    max_same_mesh_retries: int = 2
    min_devices: int = 1
    shrink_unattributed: bool = False
    regrow: bool = True
    prefer_pow2: bool = True

    @classmethod
    def from_options(cls) -> "ElasticPolicy":
        """Policy from the runtime options DB (``-elastic_*`` flags)."""
        opt = global_options()
        p = cls()
        p.enabled = opt.get_bool("elastic_enable", p.enabled)
        p.max_same_mesh_retries = opt.get_int(
            "elastic_max_same_mesh_retries", p.max_same_mesh_retries)
        p.min_devices = opt.get_int("elastic_min_devices", p.min_devices)
        p.shrink_unattributed = opt.get_bool(
            "elastic_shrink_unattributed", p.shrink_unattributed)
        p.regrow = opt.get_bool("elastic_regrow", p.regrow)
        return p


def _largest_pow2_at_most(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n >= 1 else 0


class MeshRebuilder:
    """Plans and executes degraded-mesh rebuilds (module docstring)."""

    def __init__(self, policy: ElasticPolicy | None = None):
        self.policy = policy or ElasticPolicy()

    # ---- planning ----------------------------------------------------------
    def survivors(self, comm, lost=frozenset()):
        """Mesh members not marked lost — by the sticky fault registry
        (:func:`resilience.faults.lost_devices`) or the caller's extra
        attribution set (a HealthMonitor classification)."""
        dead = set(int(d) for d in lost) | set(_faults.lost_devices())
        return [d for d in comm.devices if int(d.id) not in dead]

    def shrunk_comm(self, comm, lost=frozenset()):
        """The largest viable STRICTLY smaller communicator over
        surviving devices, or None when no viable smaller mesh exists
        (already at ``min_devices``, every device lost, or the failures
        are unattributed and speculative shrinking is off)."""
        from ..parallel.mesh import DeviceComm
        cur = comm.size
        surv = self.survivors(comm, lost)
        n = len(surv)
        if n < 1 or cur <= 1:
            return None
        if n < cur:
            # attributed: the largest ladder size the survivors support
            size = _largest_pow2_at_most(n) if self.policy.prefer_pow2 \
                else n
        elif self.policy.shrink_unattributed:
            # unattributed: nothing to exclude — halve speculatively
            # (the ladder bottoms out at min_devices, bounding guesses)
            size = _largest_pow2_at_most(cur - 1)
        else:
            return None
        if size < max(1, self.policy.min_devices) or size >= cur:
            return None
        return DeviceComm(devices=surv[:size], axis=comm.axis)

    def grown_comm(self, comm, full_comm=None):
        """The ladder's UPWARD direction: the largest viable STRICTLY
        larger communicator over currently-HEALTHY members of
        ``full_comm`` (the mesh the session was originally built on —
        re-grow never exceeds what the caller provisioned; defaults to
        the whole process device set), or None when no strictly larger
        healthy mesh exists (nothing healed, the heal was partial below
        the next pow2 rung, or the session never shrank). The symmetric
        twin of :meth:`shrunk_comm`, consulted when a
        :class:`~.faults.HealthMonitor` observes :func:`~.faults.heal`.
        """
        from ..parallel.mesh import DeviceComm
        if not self.policy.regrow:
            return None
        if full_comm is None:
            full_comm = DeviceComm()
        healthy = self.survivors(full_comm)
        n = len(healthy)
        cur = comm.size
        if n <= cur:
            return None
        size = _largest_pow2_at_most(n) if self.policy.prefer_pow2 else n
        if size <= cur:
            return None
        return DeviceComm(devices=healthy[:size], axis=comm.axis)


def rebuild_operator(mat, comm_new):
    """Re-place an operator's operands on another communicator.

    Matrix-free operators expose ``with_comm`` (e.g.
    :class:`models.stencil.StencilPoisson3D` — geometry re-derived for
    the new device count); CSR-backed :class:`core.mat.Mat` round-trips
    through its host CSR. Raises :class:`ValueError` when neither path
    exists (the escalation then falls through to the original error) or
    when the operator's sharding constraints reject the new size.
    """
    if hasattr(mat, "with_comm"):
        return mat.with_comm(comm_new)
    if hasattr(mat, "to_scipy"):
        from ..core.mat import Mat
        m2 = Mat.from_scipy(comm_new, mat.to_scipy(), dtype=mat.dtype)
        ns = getattr(mat, "nullspace", None)
        if ns is not None:
            m2.set_nullspace(ns)
        return m2
    raise ValueError(
        f"operator {type(mat).__name__} cannot be rebuilt on a new mesh: "
        "no with_comm() and no to_scipy() — provide one to make it "
        "elastic")


def rebuild_ksp(ksp, mat_new):
    """Rebind a KSP session to ``mat_new`` and its communicator.

    Builds a fresh PC of the same type with the same tunables (factors
    are re-set-up — placed on the new mesh — by ``set_up``), points the
    KSP's comm at the new mesh, and leaves compiled-program and ABFT
    checksum caches to re-key naturally on the new operator identity and
    mesh fingerprint (a previously AOT-exported program for this
    geometry loads from disk instead of re-tracing — utils/aot).
    """
    from ..solvers.pc import PC
    old_pc = ksp.get_pc()
    comm_new = mat_new.comm
    pc = PC(comm_new)
    pc.set_type(old_pc.get_type())
    for attr in ("sor_omega", "asm_overlap", "factor_fill",
                 "gamg_threshold", "gamg_coarse_size", "gamg_max_levels",
                 "mg_smoother", "bjacobi_blocks", "setup_device",
                 "_factor_solver_type"):
        if hasattr(old_pc, attr):
            setattr(pc, attr, getattr(old_pc, attr))
    ksp.comm = comm_new
    ksp.set_pc(pc)
    ksp.set_operators(mat_new)
    ksp.set_up()                  # PC factors placed on the new mesh NOW
    return ksp


def rebind_vec(vec, new):
    """Re-point a caller's Vec at new-mesh storage IN PLACE — the object
    identity the driver holds stays valid across the shrink (the same
    contract retry.py's same-mesh restore keeps via ``x.data = x2.data``,
    extended to the comm/layout that change with the mesh size)."""
    vec.comm = new.comm
    vec.layout = new.layout
    vec.n = new.n
    vec.data = new.data
    return vec


def replant_vectors(comm_new, mat_new, *vecs):
    """Host-round-trip re-placement of vectors onto ``comm_new`` (the
    in-memory path for operators without a persisted checkpoint). Each
    input Vec is rebound in place; returns them."""
    from ..core.vec import Vec
    out = []
    for v in vecs:
        nv = Vec.from_global(comm_new, v.to_numpy(), dtype=mat_new.dtype,
                             layout=mat_new.layout)
        out.append(rebind_vec(v, nv))
    return out


def warm(ksp, widths=()):
    """Pre-build (trace+compile, or AOT-load) the rebuilt session's
    programs for the new geometry by dispatching zero-RHS solves — a
    zero right-hand side converges at iteration 0, so each warm costs
    one launch and no iterations. ``widths`` re-warms the batched block
    programs a serving session dispatches (serving/server.py re-warms
    the widths it has seen)."""
    from ..core.vec import Vec
    mat = ksp.get_operators()[0]
    comm = mat.comm
    n = int(mat.shape[0])
    dt = np.dtype(mat.dtype)
    x0 = Vec(comm, n, dtype=dt, layout=getattr(mat, "layout", None))
    b0 = Vec(comm, n, dtype=dt, layout=getattr(mat, "layout", None))
    ksp.solve(b0, x0)
    for w in sorted(set(int(w) for w in widths if int(w) > 0)):
        ksp.solve_many(np.zeros((n, w), dtype=dt))
    return ksp


def shrink_solve_session(ksp, comm_new, *, checkpoint_path=None, b=None,
                         x=None, B=None, X=None, many=False):
    """Reshard a failed solve onto ``comm_new`` and rebuild the session.

    The iterate/RHS state moves through the elastic checkpoint when one
    was persisted (``checkpoint_path`` — the authoritative route: the
    checkpoint holds the last verified/partial iterate the failure left
    behind), else through an in-memory host round trip (matrix-free
    operators). Single-RHS mode rebinds the caller's ``b``/``x`` Vecs in
    place; batched mode restores the ``(n, nrhs)`` blocks into the
    caller's writable ``X`` host array. Returns the checkpoint's stored
    iteration (0 when unknown/in-memory).

    Raises ``ValueError`` when the operator cannot be rebuilt on the new
    size (callers treat that as "cannot shrink" and fall through to the
    original failure).
    """
    mat = ksp.get_operators()[0]
    iteration = 0
    if many:
        if checkpoint_path is not None:
            from ..utils.checkpoint import load_solve_state_many
            mat2, X2, _B2, iteration = load_solve_state_many(
                checkpoint_path, comm_new)
            X[...] = X2.astype(X.dtype, copy=False)
        else:
            mat2 = rebuild_operator(mat, comm_new)
        rebuild_ksp(ksp, mat2)
        return iteration
    if checkpoint_path is not None:
        from ..utils.checkpoint import load_solve_state
        mat2, x2, b2, iteration = load_solve_state(checkpoint_path,
                                                   comm_new)
        rebuild_ksp(ksp, mat2)
        rebind_vec(x, x2)
        rebind_vec(b, b2)
    else:
        mat2 = rebuild_operator(mat, comm_new)
        rebuild_ksp(ksp, mat2)
        replant_vectors(comm_new, mat2, x, b)
    return iteration


def regrow_solve_session(ksp, comm_new, *, checkpoint_path=None, b=None,
                         x=None, B=None, X=None, many=False):
    """Reshard a solve session onto a LARGER mesh after a heal — the
    upward twin of :func:`shrink_solve_session`, with the identical
    resume contract: the iterate/RHS state moves through the elastic
    checkpoint (mesh-portable in BOTH directions — the format never
    encoded a device count) or the in-memory host round trip, the
    operands / PC factors / ABFT checksums are re-placed on the grown
    geometry, and the returned iteration is where the resumed solve
    continues from (never 0 when a checkpoint carried progress).

    The resharding machinery is direction-agnostic by construction, so
    this delegates; the separate name keeps call sites honest about
    which way the ladder moved (telemetry/event kinds differ)."""
    return shrink_solve_session(ksp, comm_new,
                                checkpoint_path=checkpoint_path,
                                b=b, x=x, B=B, X=X, many=many)
