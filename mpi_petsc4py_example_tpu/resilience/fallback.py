"""Graceful-degradation chains for Krylov solves.

A Krylov breakdown (``DIVERGED_BREAKDOWN``) or a blown-up residual
(``DIVERGED_NANORINF``) is not the end of the solve — it is a signal that
the METHOD, not the problem, failed: CG on a matrix that turned out
indefinite, BiCG hitting a serendipitous zero inner product. The
:class:`KSPFallbackChain` escalates through progressively more robust
methods (default ``cg → bcgs → gmres → preonly+lu``, the last being the
direct path — device-dense or host-SuperLU via ``KSP._solve_hostlu`` —
that cannot break down), restoring the pristine initial guess before each
stage so a NaN-poisoned iterate never seeds the next method.

``RESOURCE_EXHAUSTED`` device failures (``failure_class='oom'``) get the
orthogonal degradation: retry the SAME method at reduced precision
(float64→float32, complex128→complex64 — utils/dtypes.py), halving
device-memory pressure at the cost of achievable tolerance.

Every escalation is a :class:`utils.convergence.RecoveryEvent` on the
returned result — the full trail of what was tried, in order, with the
reason each stage failed.
"""

from __future__ import annotations

import numpy as np

from ..utils.convergence import ConvergedReason, RecoveryEvent, SolveResult
from ..utils.errors import DeviceExecutionError

# escalation order: each entry is (ksp_type, pc_type-or-None). None keeps
# the chain owner's preconditioner.
DEFAULT_ESCALATION = (("bcgs", None), ("gmres", None), ("preonly", "lu"))

# reason codes that mean "the method broke, a stronger one may not"
DEFAULT_ESCALATE_ON = (ConvergedReason.DIVERGED_BREAKDOWN,
                       ConvergedReason.DIVERGED_NANORINF)

_REDUCED = {np.dtype(np.float64): np.float32,
            np.dtype(np.complex128): np.complex64}


def reduced_dtype(dtype):
    """The reduced-precision retry dtype, or None when already minimal."""
    return _REDUCED.get(np.dtype(dtype))


class KSPFallbackChain:
    """Escalate a KSP solve through more robust methods on breakdown/NaN.

    ``methods`` overrides the escalation stages (sequence of ``ksp_type``
    strings or ``(ksp_type, pc_type)`` pairs, tried after the KSP's own
    configuration); ``direct=False`` drops the terminal direct stage;
    ``reduced_precision=False`` disables the oom→lower-precision retry;
    ``escalate_on`` overrides the escalating reason codes.

    The chain leaves the LAST WORKING configuration on the KSP
    (``keep_working_config=True``, the default): staying degraded is the
    point of graceful degradation — subsequent solves skip the broken
    method. Set it False to restore the original type/pc after each call.
    A reduced-precision recovery is the exception: it runs on a scratch
    solver (the owner KSP's operators stay full-precision), so it is
    per-solve, not sticky — but the converted operator and scratch KSP are
    cached on the chain, so repeated oom recoveries pay the conversion
    once.
    """

    def __init__(self, ksp, methods=None, *, direct: bool = True,
                 reduced_precision: bool = True,
                 escalate_on: tuple = DEFAULT_ESCALATE_ON,
                 keep_working_config: bool = True):
        self.ksp = ksp
        self.reduced_precision = reduced_precision
        self.escalate_on = tuple(escalate_on)
        self.keep_working_config = keep_working_config
        self._lo_cache = None          # (mat, ksp_type) -> scratch solver
        if methods is None:
            stages = [s for s in DEFAULT_ESCALATION
                      if direct or s[0] != "preonly"]
        else:
            stages = [(m, None) if isinstance(m, str) else tuple(m)
                      for m in methods]
            if direct and all(t != "preonly" for t, _ in stages):
                stages.append(("preonly", "lu"))
        self.stages = tuple(stages)

    # ---- internals ---------------------------------------------------------
    def _solve_reduced(self, b, x, events, attempt):
        """Retry the CURRENT configuration at reduced precision (the
        RESOURCE_EXHAUSTED degradation). Returns a SolveResult or None
        when no lower precision exists / operators are matrix-free. The
        scratch solver runs on the chain, never on the owner KSP — its
        converted operator is cached so repeated recoveries pay the
        matrix conversion once."""
        from ..core.mat import Mat
        from ..core.vec import Vec
        from ..solvers.ksp import KSP
        ksp = self.ksp
        mat = ksp.get_operators()[0]
        rdt = reduced_dtype(mat.dtype)
        if rdt is None or not hasattr(mat, "to_scipy"):
            return None
        comm = mat.comm
        rdt = np.dtype(rdt)
        events.append(RecoveryEvent(
            kind="precision", attempt=attempt,
            detail=f"{np.dtype(mat.dtype)}->{rdt}", error_class="oom"))
        cache_token = (mat, ksp.get_type(), ksp.get_pc().get_type())
        if self._lo_cache is not None and self._lo_cache[0] == cache_token:
            sub = self._lo_cache[1]
        else:
            mat_lo = Mat.from_scipy(comm, mat.to_scipy().astype(rdt),
                                    dtype=rdt)
            sub = KSP().create(comm)
            sub.set_operators(mat_lo)
            sub.set_type(ksp.get_type())
            sub.get_pc().set_type(ksp.get_pc().get_type())
            self._lo_cache = (cache_token, sub)
        # float32 cannot reach float64 tolerances: floor rtol at sqrt(eps)
        rtol = max(ksp.rtol, float(np.sqrt(np.finfo(rdt).eps)))
        sub.set_tolerances(rtol=rtol, atol=ksp.atol, divtol=ksp.divtol,
                           max_it=ksp.max_it)
        b_lo = Vec.from_global(comm, b.to_numpy().astype(rdt), dtype=rdt)
        x_lo = Vec.from_global(comm, x.to_numpy().astype(rdt), dtype=rdt)
        result = sub.solve(b_lo, x_lo)
        x.set_global(x_lo.to_numpy().astype(
            np.dtype(str(mat.dtype)), copy=False))
        return result

    # ---- solve -------------------------------------------------------------
    def solve(self, b, x) -> SolveResult:
        """Solve ``A x = b``, escalating until a method converges or the
        chain is exhausted. The last stage's result is returned either
        way, carrying the full ``recovery_events`` trail."""
        ksp = self.ksp
        config0 = (ksp.get_type(), ksp.get_pc().get_type())
        # pristine initial guess: restored before every escalation so a
        # poisoned iterate never seeds the next method. COPIED, not
        # referenced: the solve programs DONATE the iterate buffer
        # (krylov donate=True), so x.data is consumed by each stage —
        # a bare reference here would be a deleted array by the time a
        # fallback needs it
        import jax.numpy as jnp
        x0_data = jnp.copy(x.data)
        events: list[RecoveryEvent] = []
        # stage dedup happens at SOLVE time against the KSP's current type:
        # after a kept escalation (say cg->bcgs), the next call must not
        # try bcgs twice
        plan = ((config0[0], None),) + tuple(
            s for s in self.stages if s[0] != config0[0])
        attempt = 0
        result = None
        tried_precision = False
        precision_success = False
        last_config = config0 + (None,)
        try:
            for ksp_type, pc_type in plan:
                attempt += 1
                if attempt > 1:
                    # hand each stage its OWN donable copy — the stage's
                    # solve consumes what it is given
                    x.data = jnp.copy(x0_data)
                ksp.set_type(ksp_type)
                if pc_type is not None:
                    ksp.get_pc().set_type(pc_type)
                last_config = (ksp_type, pc_type or config0[1], None)
                try:
                    result = ksp.solve(b, x)
                except DeviceExecutionError as exc:
                    if (exc.failure_class == "oom" and self.reduced_precision
                            and not tried_precision):
                        tried_precision = True
                        result = self._solve_reduced(b, x, events, attempt)
                        if result is not None and result.converged:
                            precision_success = True
                            last_config = (ksp_type, last_config[1],
                                           "reduced-precision")
                            break
                        if result is not None:
                            continue
                    if attempt >= len(plan):
                        raise
                    events.append(RecoveryEvent(
                        kind="fallback", attempt=attempt,
                        detail=f"{ksp_type}: {exc.failure_class} "
                               "device failure",
                        error_class=exc.failure_class))
                    continue
                if result.reason not in self.escalate_on:
                    break
                if attempt < len(plan):
                    events.append(RecoveryEvent(
                        kind="fallback", attempt=attempt,
                        detail=f"{ksp_type}->{plan[attempt][0]}",
                        error_class=ConvergedReason.name(result.reason),
                        iterations=result.iterations))
        finally:
            # restore the owner's configuration on EVERY exit that did not
            # end on a genuinely working config — including a raising last
            # stage (the caller's KSP must never stay pinned to a stage
            # that failed). A reduced-precision success lives on the
            # scratch solver, not the owner, so it restores too.
            if not self.keep_working_config or precision_success or (
                    result is None or not result.converged):
                ksp.set_type(config0[0])
                ksp.get_pc().set_type(config0[1])
        if result is None:      # every stage raised; unreachable normally
            raise DeviceExecutionError(
                "KSPFallbackChain", RuntimeError("all stages failed"))
        result.attempts = attempt
        result.recovery_events = events
        # (type, pc, note): the configuration that produced the returned
        # result; note='reduced-precision' marks the scratch-solver path
        self.last_config = last_config
        return result
