"""Retry/backoff with checkpoint-resume around the KSP solve boundary.

The reference's failure story is an opaque ``MPI_Abort``; here a retriable
device failure (a TPU worker crash/restart — ``DeviceExecutionError`` with
``failure_class='unavailable'``) mid-solve is recovered instead of fatal:

1. the best iterate reached so far (the solve boundary restores partial
   state, see ``ksp.program`` in resilience/faults.py) is CHECKPOINTED with
   :func:`utils.checkpoint.save_solve_state` — atomic, elastic across mesh
   sizes;
2. the policy's deterministic exponential backoff waits out the worker
   restart (sleeps run on HOST, outside any traced program — tpslint
   TPS001 stays clean by construction);
3. operators are REBUILT from the checkpoint (fresh device buffers — stale
   buffers on a restarted worker are exactly what must not be trusted) and
   the solve RESUMES from the restored iterate via
   ``set_initial_guess_nonzero(True)``, converging in the iterations the
   crash left over rather than starting cold.

Every action is recorded as a :class:`utils.convergence.RecoveryEvent` on
the returned result's ``recovery_events`` trail.

Same-mesh retries assume the failure is TRANSIENT — the worker restarts
and the identical mesh works again. A PERSISTENTLY lost device breaks
that assumption: every same-mesh attempt fails identically. The
escalation ladder past the retry stage is the ELASTIC one
(resilience/elastic.py): once the :class:`~.faults.HealthMonitor`
classifies the failure pattern as a persistent loss (or the same-mesh
budget is exhausted with a known-lost device), the wrappers reshard the
last checkpointed/in-memory iterate onto the largest viable smaller mesh
and RESUME from that iteration — a ``mesh_shrink``
:class:`~..utils.convergence.RecoveryEvent` with the old/new device
counts, a fresh same-mesh retry budget on the degraded mesh, and the
ladder bounded below by ``-elastic_min_devices``.

With no failure, :func:`resilient_solve` is exactly one ``ksp.solve`` —
same compiled program, zero extra XLA programs, zero device round trips.
"""

from __future__ import annotations

import os
import tempfile
import time
from dataclasses import dataclass

from ..telemetry import flight as _flight
from ..telemetry import spans as _telemetry
from ..utils.checkpoint import (load_solve_state, load_solve_state_many,
                                save_solve_state, save_solve_state_many)
from ..utils.convergence import (BatchedSolveResult, RecoveryEvent,
                                 SolveResult)
from ..utils.errors import DeviceExecutionError, SilentCorruptionError


def _push(events: list, e: RecoveryEvent) -> RecoveryEvent:
    """Append one recovery event AND mirror it into the telemetry flight
    recorder (when armed) — the post-mortem ring then holds the same
    ladder the result's ``recovery_events`` trail reports."""
    events.append(e)
    if _telemetry.enabled():
        _flight.recorder.record_event(
            "recovery", stage=e.kind, attempt=e.attempt, detail=e.detail,
            error_class=e.error_class, detector=e.detector,
            iterations=e.iterations, old_devices=e.old_devices,
            new_devices=e.new_devices, delay=e.delay)
    return e


@dataclass
class RetryPolicy:
    """When and how to retry a failed solve.

    Delays are exponential (``base_delay * backoff_factor**retry``) capped
    at ``max_delay`` — and DETERMINISTIC by default (``jitter=0``): tests
    assert exact backoff sequences. Production fleets that need
    thundering-herd protection set ``jitter`` (a fraction of the delay,
    drawn reproducibly from ``jitter_seed``).

    ``retriable_classes`` keys off ``DeviceExecutionError.failure_class``
    (utils/errors.FAILURE_CLASSES): 'unavailable' is retriable as-is;
    'detected_sdc' (a silent corruption caught by the ABFT/monitor
    guard) retries WITHOUT backoff — there is no crashed worker to wait
    out, the solve re-enters immediately from the verified iterate the
    solve boundary rolled back to; 'oom' needs a cheaper configuration
    (the fallback chain's reduced-precision move, resilience/fallback.py),
    and 'callback' / 'unsupported' cannot succeed on retry at all.
    """
    max_attempts: int = 3
    base_delay: float = 0.5
    backoff_factor: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.0
    jitter_seed: int = 0
    retriable_classes: tuple = ("unavailable", "detected_sdc")
    sleep: object = time.sleep     # injectable for tests (recorded delays)

    @classmethod
    def serving(cls) -> "RetryPolicy":
        """The solve server's default policy (serving/server.py):
        clients are WAITING on futures, so the worker-restart backoff is
        two orders shorter than the batch default (50 ms base, 1 s cap)
        while staying deterministic; DETECTED_SDC re-entries are
        immediate either way. ``-solve_server_retry_delay`` overrides
        the base delay at runtime."""
        return cls(max_attempts=3, base_delay=0.05, max_delay=1.0)

    def delay(self, retry_index: int) -> float:
        """Backoff before retry ``retry_index`` (0-based)."""
        d = min(self.base_delay * self.backoff_factor ** retry_index,
                self.max_delay)
        if self.jitter:
            import random
            rng = random.Random((self.jitter_seed, retry_index))
            d *= 1.0 + self.jitter * rng.random()
        return d

    def should_retry(self, exc: Exception) -> bool:
        return (isinstance(exc, DeviceExecutionError)
                and exc.failure_class in self.retriable_classes)


def _verify_true_residual(ksp, b, x):
    """Host-checked TRUE residual of the recovered iterate against the
    KSP's own tolerance target: ``(ok, rel_residual)``. The verification
    channel is independent of the (possibly corrupted) solve program —
    one plain operator apply plus host norms. A zero target (norm-none /
    fixed-iteration solves set rtol=atol=0 — there is no convergence
    contract to hold the answer to) passes with the residual reported
    informationally."""
    import numpy as np
    mat = ksp.get_operators()[0]
    bh = np.asarray(b.to_numpy())
    ax = np.asarray(mat.mult(x).to_numpy())
    rn = float(np.linalg.norm(bh - ax))
    bn = float(np.linalg.norm(bh))
    target = max(ksp.rtol * bn, ksp.atol)
    # 1.05: device-vs-host norm rounding slack (the repo-wide convention)
    ok = target <= 0 or rn <= target * 1.05
    return ok, rn / bn if bn > 0 else rn


def _verify_true_residual_many(ksp, B, X):
    """Per-column host-checked TRUE residuals of the recovered block:
    ``(all_ok, worst_rel_residual)``. Zero-target columns (rtol=atol=0,
    the fixed-iteration contract) pass — see _verify_true_residual."""
    import numpy as np
    mat = ksp.get_operators()[0]
    B = np.asarray(B)
    X = np.asarray(X)
    if hasattr(mat, "to_scipy"):
        R = B - mat.to_scipy() @ X
    else:
        from ..core.vec import Vec
        cols = []
        for j in range(X.shape[1]):
            xv = Vec.from_global(mat.comm, X[:, j], dtype=mat.dtype,
                                 layout=mat.layout)
            cols.append(np.asarray(mat.mult(xv).to_numpy()))
        R = B - np.stack(cols, axis=1)
    rn = np.linalg.norm(R, axis=0)
    bn = np.linalg.norm(B, axis=0)
    targets = np.maximum(ksp.rtol * bn, ksp.atol)
    ok = bool(np.all((targets <= 0) | (rn <= targets * 1.05)))
    rres = float(np.max(rn / np.where(bn > 0, bn, 1.0)))
    return ok, rres


def _reraise_if_rebuild_failed(rebuild_exc, original):
    """The SAME-MESH checkpoint reload failed. When the rebuild died the
    way the solve did — a device-shaped failure, e.g. placement onto a
    mesh that has genuinely lost hardware — surface the ORIGINAL
    classified solve error (chained): the mesh is the problem, and the
    caller's recovery contract is written in DeviceExecutionError terms.
    Anything else (a corrupt checkpoint's ValueError) propagates as
    itself."""
    name = type(rebuild_exc).__name__
    if ("XlaRuntimeError" in name or "JaxRuntimeError" in name
            or isinstance(rebuild_exc, DeviceExecutionError)):
        raise original from rebuild_exc
    raise rebuild_exc


def _failure_iteration(exc) -> int:
    """Iterations of real partial state a failure left in the caller's
    iterate: SilentCorruptionError carries it directly; fail-stop faults
    carry it on the wrapped runtime error (faults.Fault.error). 0 when
    unknown — the checkpoint then just records 'progress unquantified',
    the iterate itself still holds whatever was reached."""
    it = getattr(exc, "iteration", None)
    if it is None:
        it = getattr(getattr(exc, "original", None), "iteration", None)
    return int(it or 0)


class _ElasticEscalation:
    """Per-solve elastic state shared by the two resilient wrappers.

    Owns the :class:`~.faults.HealthMonitor` (consecutive-unavailable
    evidence, reset on success) and executes the shrink step: plan the
    degraded mesh, reshard the checkpointed/in-memory state onto it via
    :func:`~.elastic.shrink_solve_session`, and record the
    ``mesh_shrink`` event. ``None``-policy construction reads the
    ``-elastic_*`` runtime flags.
    """

    def __init__(self, policy=None):
        from .elastic import ElasticPolicy, MeshRebuilder
        from .faults import HealthMonitor
        self.policy = (policy if policy is not None
                       else ElasticPolicy.from_options())
        self.monitor = HealthMonitor(
            threshold=self.policy.max_same_mesh_retries)
        self.rebuilder = MeshRebuilder(self.policy)
        # the mesh this solve STARTED on: the re-grow ceiling — a heal
        # may rebuild a shrunk session back up, never past what the
        # caller provisioned (None until a shrink actually happened)
        self.orig_comm = None

    def record(self, exc):
        """Count one failure toward the persistent-loss classification
        (``unavailable`` failures only — OOM/SDC have their own
        escalations)."""
        if getattr(exc, "failure_class", "") == "unavailable":
            self.monitor.record(exc)

    def plan(self, ksp, exc, budget_exhausted: bool):
        """The degraded communicator to rebuild onto, or None when the
        shrink stage must not (yet) engage: escalate once the failure is
        CLASSIFIED persistent — a current mesh member is in the sticky
        lost registry (ground truth: a fired ``device.lost`` or an
        explicit ``mark_lost``; same-mesh retries on such a mesh cannot
        succeed, so no evidence-gathering retries are owed), or the
        monitor's consecutive-failure evidence reached its threshold —
        or as the last rung before giving up when the same-mesh budget
        is spent."""
        from . import faults as _faults
        if (not self.policy.enabled
                or getattr(exc, "failure_class", "") != "unavailable"):
            return None
        # RE-GROW rung (the ladder's upward direction): this solve
        # previously shrank, a heal has been observed since, and the
        # healed hardware supports a strictly larger mesh — reshard the
        # checkpointed iterate UP and resume there instead of retrying
        # on degraded capacity. Bounded by orig_comm: only a session
        # this escalation shrank may grow, and never past its original
        # provisioning.
        if (self.policy.regrow and self.orig_comm is not None
                and self.monitor.heal_observed()):
            grown = self.rebuilder.grown_comm(ksp.comm, self.orig_comm)
            if grown is not None:
                return grown
        ids = set(getattr(ksp.comm, "device_ids", ()))
        registry_hit = any(d in ids for d in _faults.lost_devices())
        if not (registry_hit or self.monitor.persistent()
                or budget_exhausted):
            return None
        return self.rebuilder.shrunk_comm(ksp.comm,
                                          self.monitor.lost_devices())

    def reshard(self, ksp, comm_new, events, attempt, *, persisted, path,
                b=None, x=None, B=None, X=None, many=False) -> bool:
        """Execute the rebuild onto ``comm_new`` — DOWN (mesh_shrink) or
        UP (mesh_regrow, after a heal); False when the operator cannot
        be rebuilt there (callers fall through to the original
        failure)."""
        from .elastic import shrink_solve_session
        from ..utils.profiling import record_mesh_regrow, record_mesh_shrink
        old_comm = ksp.comm
        old_n = old_comm.size
        growing = comm_new.size > old_n
        t0 = time.perf_counter()
        try:
            it0 = shrink_solve_session(
                ksp, comm_new,
                checkpoint_path=path if persisted else None,
                b=b, x=x, B=B, X=X, many=many)
        except ValueError:
            return False
        wall = time.perf_counter() - t0
        if growing:
            record_mesh_regrow(old_n, comm_new.size, wall)
        else:
            if self.orig_comm is None:
                # the first shrink: remember the provisioned mesh — the
                # re-grow ceiling a later heal may rebuild back up to
                self.orig_comm = old_comm
            record_mesh_shrink(old_n, comm_new.size, wall)
        _push(events, RecoveryEvent(
            kind="mesh_regrow" if growing else "mesh_shrink",
            attempt=attempt,
            detail=(f"rebuilt {old_n} -> {comm_new.size} devices in "
                    f"{wall:.3f}s; resuming from iteration {it0}"),
            error_class="unavailable", iterations=it0,
            old_devices=old_n, new_devices=comm_new.size))
        # the resharded mesh gets fresh consecutive-failure evidence (the
        # sticky faults.lost_devices registry keeps the excluded devices
        # out of any FURTHER shrink planning either way)
        self.monitor.healthy()
        return True


def default_checkpoint_path(ksp=None) -> str:
    """Default solve-state checkpoint path, unique per process AND per
    solver object — concurrent resilient solves in one process must never
    restore each other's operators from a shared file."""
    tag = f"_{id(ksp):x}" if ksp is not None else ""
    return os.path.join(tempfile.gettempdir(),
                        f"tpu_solve_ckpt_{os.getpid()}{tag}.npz")


def resilient_solve(ksp, b, x, policy: RetryPolicy | None = None, *,
                    checkpoint_path: str | None = None,
                    elastic=None) -> SolveResult:
    """``ksp.solve(b, x)`` that survives retriable device failures.

    On a retriable ``DeviceExecutionError`` (per ``policy``), checkpoints
    the best iterate, backs off, rebuilds the operators from the
    checkpoint, and resumes from the restored iterate — up to
    ``policy.max_attempts`` attempts per mesh. PERSISTENT device loss
    escalates past same-mesh retries (module docstring): once the
    health monitor classifies the pattern — or as the last rung before
    giving up — the solve is resharded onto the largest viable smaller
    mesh and resumes from the checkpointed iterate, with a fresh
    same-mesh budget there. Non-retriable failures and exhausted
    policies (with no viable smaller mesh) re-raise the original error.

    ``checkpoint_path`` defaults to :func:`default_checkpoint_path`.
    Matrix-free operators (no ``to_scipy``) skip persistence — retries
    and shrinks still resume from the in-memory iterate. ``elastic``
    is an :class:`~.elastic.ElasticPolicy` (default: the ``-elastic_*``
    runtime flags).

    Returns the converged attempt's :class:`SolveResult` with ``attempts``
    and the ``recovery_events`` trail filled in.
    """
    sp = _telemetry.span("resilient.solve", many=False)
    try:
        with sp:
            result = _resilient_solve_impl(ksp, b, x, policy,
                                           checkpoint_path, elastic)
            sp.set_attrs(attempts=result.attempts,
                         recoveries=len(result.recovery_events),
                         iterations=result.iterations,
                         converged=result.converged)
            return result
    # tpslint: disable=TPS005 — dump-and-reraise: an error escaping the
    # resilient wrapper is by definition unrecovered, and the flight-ring
    # dump must fire for EVERY class of it; nothing is swallowed (the
    # bare raise re-raises immediately). The dump runs AFTER the span
    # context exited, so the failed solve's own span tree is already in
    # the ring and lands in the post-mortem.
    except Exception:  # noqa: BLE001
        _flight.auto_dump("unrecovered resilient_solve failure")
        raise


def _resilient_solve_impl(ksp, b, x, policy, checkpoint_path,
                          elastic) -> SolveResult:
    policy = policy or RetryPolicy()
    path = checkpoint_path or default_checkpoint_path(ksp)
    esc = _ElasticEscalation(elastic)
    events: list[RecoveryEvent] = []
    guess_flag0 = ksp._initial_guess_nonzero
    attempt = 1        # total attempts across meshes (result.attempts)
    mesh_attempt = 1   # attempts on the CURRENT mesh (the retry budget)
    try:
        while True:
            try:
                result = ksp.solve(b, x)
                break
            except DeviceExecutionError as exc:
                esc.record(exc)
                retriable = policy.should_retry(exc)
                exhausted = mesh_attempt >= policy.max_attempts
                comm_new = (esc.plan(ksp, exc, exhausted)
                            if retriable else None)
                if comm_new is None and (exhausted or not retriable):
                    raise
                detector = getattr(exc, "detector", "")
                sdc = exc.failure_class == "detected_sdc"
                _push(events, RecoveryEvent(
                    kind="fault", attempt=attempt, detail=str(exc),
                    error_class=exc.failure_class, detector=detector))
                mat = ksp.get_operators()[0]
                persisted = hasattr(mat, "to_scipy")
                if persisted:
                    # for DETECTED_SDC the solve boundary already rolled
                    # x back to the last VERIFIED iterate — the
                    # checkpoint persists exactly that rollback target
                    save_solve_state(path, mat, x, b,
                                     iteration=_failure_iteration(exc))
                    _push(events, RecoveryEvent(
                        kind="checkpoint", attempt=attempt, detail=path))
                if comm_new is not None:
                    # ELASTIC escalation: same-mesh retrying is futile —
                    # reshard the checkpointed (or in-memory) iterate
                    # onto the degraded mesh (or, after a heal, back UP
                    # onto the repaired one) and resume from it
                    if comm_new.size > ksp.comm.size:
                        shsp = _telemetry.span(
                            "resilient.regrow",
                            old_devices=int(ksp.comm.size),
                            new_devices=int(comm_new.size))
                    else:
                        shsp = _telemetry.span(
                            "resilient.shrink",
                            old_devices=int(ksp.comm.size),
                            new_devices=int(comm_new.size))
                    with shsp:
                        ok = esc.reshard(ksp, comm_new, events, attempt,
                                         persisted=persisted, path=path,
                                         b=b, x=x)
                        if ok:
                            # the shrink event carries the checkpointed
                            # iteration the resumed solve continues from
                            shsp.set_attr("resumed_iteration",
                                          events[-1].iterations)
                    if not ok:
                        raise    # operator not rebuildable on that size
                    mesh_attempt = 0   # fresh budget on the new mesh
                elif sdc:
                    # no crashed worker to wait out: re-enter immediately
                    # from the verified iterate (retry.py's DETECTED_SDC
                    # escalation — the final answer is re-verified against
                    # the TRUE residual below before it is returned)
                    with _telemetry.span("resilient.rollback",
                                         detector=detector):
                        _push(events, RecoveryEvent(
                            kind="rollback", attempt=attempt,
                            detail="re-entering from verified iterate",
                            detector=detector))
                else:
                    delay = policy.delay(mesh_attempt - 1)
                    _push(events, RecoveryEvent(
                        kind="backoff", attempt=attempt, delay=delay,
                        error_class=exc.failure_class))
                    with _telemetry.span("resilient.backoff", delay=delay,
                                         error_class=exc.failure_class):
                        policy.sleep(delay)
                    if persisted:
                        # rebuild from the checkpoint: fresh device
                        # buffers (nothing from before the failure is
                        # trusted), iterate restored onto the CALLER's
                        # vector so x stays live
                        with _telemetry.span("resilient.rebuild",
                                             checkpoint=path):
                            try:
                                mat2, x2, _b2, _it = load_solve_state(
                                    path, mat.comm)
                            # tpslint: disable=TPS005 — classified and
                            # re-raised by kind immediately below
                            except Exception as rexc:  # noqa: BLE001
                                _reraise_if_rebuild_failed(rexc, exc)
                            ksp.set_operators(mat2)
                            x.data = x2.data
                ksp.set_initial_guess_nonzero(True)
                attempt += 1
                mesh_attempt += 1
                _push(events, RecoveryEvent(
                    kind="resume", attempt=attempt,
                    detail="initial_guess_nonzero from restored iterate"))
    finally:
        ksp.set_initial_guess_nonzero(guess_flag0)
    result.attempts = attempt
    result.recovery_events = events
    sdc_faults = [e for e in events if e.kind == "fault" and e.detector]
    if sdc_faults:
        # a silent corruption was recovered from: the answer must not be
        # taken on the recurrence's word — verify the TRUE residual
        # through an independent host-checked apply
        with _telemetry.span("resilient.verify") as vsp:
            ok, rres = _verify_true_residual(ksp, b, x)
            vsp.set_attrs(ok=ok, rel_residual=float(rres))
        if not ok:
            raise SilentCorruptionError(
                "resilient_solve", "verify", result.iterations,
                detail=f"recovered solve's true relative residual "
                       f"{rres:.3e} misses the tolerance target")
        _push(events, RecoveryEvent(
            kind="verify", attempt=attempt,
            detail=f"true relative residual {rres:.3e} meets target",
            detector="verify"))
        result.sdc_detections = len(sdc_faults)
    return result


def resilient_solve_many(ksp, B, X=None, policy: RetryPolicy | None = None,
                         *, checkpoint_path: str | None = None,
                         elastic=None) -> BatchedSolveResult:
    """``ksp.solve_many(B, X)`` that survives retriable device failures —
    the batched twin of :func:`resilient_solve`.

    The checkpoint carries the whole ``(n, nrhs)`` iterate/RHS blocks
    (:func:`utils.checkpoint.save_solve_state_many`): a mid-batch crash
    leaves the partial iterate BLOCK in ``X`` (the ``ksp.program`` fault
    boundary in KSP.solve_many writes it before raising), the rebuilt
    solve resumes every column from where it froze, and already-converged
    columns re-converge in O(1) iterations under the masked-convergence
    kernel. Persistent device loss escalates to a mesh shrink exactly
    like :func:`resilient_solve` — the whole block is resharded and every
    in-flight column (batch-mates included) replays from its restored
    iterate on the degraded mesh. Same zero-overhead contract: no
    failure means exactly one ``ksp.solve_many``.
    """
    sp = _telemetry.span("resilient.solve", many=True)
    try:
        with sp:
            result = _resilient_solve_many_impl(ksp, B, X, policy,
                                                checkpoint_path, elastic)
            sp.set_attrs(attempts=result.attempts,
                         recoveries=len(result.recovery_events),
                         nrhs=len(result.iterations),
                         converged=result.converged)
            return result
    # tpslint: disable=TPS005 — dump-and-reraise after the span closed
    # (see resilient_solve: the dump must include the failed span tree)
    except Exception:  # noqa: BLE001
        _flight.auto_dump("unrecovered resilient_solve_many failure")
        raise


def _resilient_solve_many_impl(ksp, B, X, policy, checkpoint_path,
                               elastic) -> BatchedSolveResult:
    import numpy as np
    policy = policy or RetryPolicy()
    path = checkpoint_path or default_checkpoint_path(ksp)
    esc = _ElasticEscalation(elastic)
    events: list[RecoveryEvent] = []
    guess_flag0 = ksp._initial_guess_nonzero
    mat = ksp.get_operators()[0]
    if isinstance(B, (list, tuple)):
        # the same Vec-stacking normalization KSP.solve_many accepts —
        # a bare asarray would mangle a list of Vecs into an object array
        B = np.stack([b.to_numpy() if hasattr(b, "to_numpy")
                      else np.asarray(b) for b in B], axis=1)
    B = np.asarray(B)
    if X is None:
        X = np.zeros(B.shape, dtype=np.dtype(mat.dtype))
    else:
        # the wrapper's resume contract needs a WRITABLE host ndarray the
        # fault boundary writes the partial iterate into — a jax array
        # (asarray of one is a read-only view) or nested list would make
        # solve_many checkpoint the stale guess or die on the in-place
        # restore below
        X = np.asarray(X)
        if not X.flags.writeable:
            X = X.copy()
    attempt = 1
    mesh_attempt = 1
    try:
        while True:
            try:
                result = ksp.solve_many(B, X)
                break
            except DeviceExecutionError as exc:
                esc.record(exc)
                retriable = policy.should_retry(exc)
                exhausted = mesh_attempt >= policy.max_attempts
                comm_new = (esc.plan(ksp, exc, exhausted)
                            if retriable else None)
                if comm_new is None and (exhausted or not retriable):
                    raise
                detector = getattr(exc, "detector", "")
                sdc = exc.failure_class == "detected_sdc"
                _push(events, RecoveryEvent(
                    kind="fault", attempt=attempt, detail=str(exc),
                    error_class=exc.failure_class, detector=detector))
                mat = ksp.get_operators()[0]
                persisted = hasattr(mat, "to_scipy")
                if persisted:
                    # on DETECTED_SDC, X already holds the per-column
                    # verified iterate block the solve boundary restored
                    save_solve_state_many(path, mat, X, B,
                                          iteration=_failure_iteration(exc))
                    _push(events, RecoveryEvent(
                        kind="checkpoint", attempt=attempt, detail=path))
                if comm_new is not None:
                    if comm_new.size > ksp.comm.size:
                        shsp = _telemetry.span(
                            "resilient.regrow",
                            old_devices=int(ksp.comm.size),
                            new_devices=int(comm_new.size))
                    else:
                        shsp = _telemetry.span(
                            "resilient.shrink",
                            old_devices=int(ksp.comm.size),
                            new_devices=int(comm_new.size))
                    with shsp:
                        ok = esc.reshard(ksp, comm_new, events, attempt,
                                         persisted=persisted, path=path,
                                         B=B, X=X, many=True)
                        if ok:
                            shsp.set_attr("resumed_iteration",
                                          events[-1].iterations)
                    if not ok:
                        raise
                    mesh_attempt = 0
                elif sdc:
                    with _telemetry.span("resilient.rollback",
                                         detector=detector):
                        _push(events, RecoveryEvent(
                            kind="rollback", attempt=attempt,
                            detail="re-entering from verified iterate "
                                   "block",
                            detector=detector))
                else:
                    delay = policy.delay(mesh_attempt - 1)
                    _push(events, RecoveryEvent(
                        kind="backoff", attempt=attempt, delay=delay,
                        error_class=exc.failure_class))
                    with _telemetry.span("resilient.backoff", delay=delay,
                                         error_class=exc.failure_class):
                        policy.sleep(delay)
                    if persisted:
                        with _telemetry.span("resilient.rebuild",
                                             checkpoint=path):
                            try:
                                mat2, X2, _B2, _it = load_solve_state_many(
                                    path, mat.comm)
                            # tpslint: disable=TPS005 — classified and
                            # re-raised by kind immediately below
                            except Exception as rexc:  # noqa: BLE001
                                _reraise_if_rebuild_failed(rexc, exc)
                            ksp.set_operators(mat2)
                            X[...] = X2.astype(X.dtype, copy=False)
                ksp.set_initial_guess_nonzero(True)
                attempt += 1
                mesh_attempt += 1
                _push(events, RecoveryEvent(
                    kind="resume", attempt=attempt,
                    detail="initial_guess_nonzero from restored "
                           "iterate block"))
    finally:
        ksp.set_initial_guess_nonzero(guess_flag0)
    result.attempts = attempt
    result.recovery_events = events
    sdc_faults = [e for e in events if e.kind == "fault" and e.detector]
    if sdc_faults:
        with _telemetry.span("resilient.verify") as vsp:
            ok, rres = _verify_true_residual_many(ksp, B, result.X)
            vsp.set_attrs(ok=ok, rel_residual=float(rres))
        if not ok:
            raise SilentCorruptionError(
                "resilient_solve_many", "verify",
                max(result.iterations, default=0),
                detail=f"recovered batch's worst true relative residual "
                       f"{rres:.3e} misses the tolerance target")
        _push(events, RecoveryEvent(
            kind="verify", attempt=attempt,
            detail=f"worst per-column true relative residual {rres:.3e} "
                   "meets target",
            detector="verify"))
        result.sdc_detections = len(sdc_faults)
    return result
