"""Retry/backoff with checkpoint-resume around the KSP solve boundary.

The reference's failure story is an opaque ``MPI_Abort``; here a retriable
device failure (a TPU worker crash/restart — ``DeviceExecutionError`` with
``failure_class='unavailable'``) mid-solve is recovered instead of fatal:

1. the best iterate reached so far (the solve boundary restores partial
   state, see ``ksp.program`` in resilience/faults.py) is CHECKPOINTED with
   :func:`utils.checkpoint.save_solve_state` — atomic, elastic across mesh
   sizes;
2. the policy's deterministic exponential backoff waits out the worker
   restart (sleeps run on HOST, outside any traced program — tpslint
   TPS001 stays clean by construction);
3. operators are REBUILT from the checkpoint (fresh device buffers — stale
   buffers on a restarted worker are exactly what must not be trusted) and
   the solve RESUMES from the restored iterate via
   ``set_initial_guess_nonzero(True)``, converging in the iterations the
   crash left over rather than starting cold.

Every action is recorded as a :class:`utils.convergence.RecoveryEvent` on
the returned result's ``recovery_events`` trail.

With no failure, :func:`resilient_solve` is exactly one ``ksp.solve`` —
same compiled program, zero extra XLA programs, zero device round trips.
"""

from __future__ import annotations

import os
import tempfile
import time
from dataclasses import dataclass

from ..utils.checkpoint import (load_solve_state, load_solve_state_many,
                                save_solve_state, save_solve_state_many)
from ..utils.convergence import (BatchedSolveResult, RecoveryEvent,
                                 SolveResult)
from ..utils.errors import DeviceExecutionError


@dataclass
class RetryPolicy:
    """When and how to retry a failed solve.

    Delays are exponential (``base_delay * backoff_factor**retry``) capped
    at ``max_delay`` — and DETERMINISTIC by default (``jitter=0``): tests
    assert exact backoff sequences. Production fleets that need
    thundering-herd protection set ``jitter`` (a fraction of the delay,
    drawn reproducibly from ``jitter_seed``).

    ``retriable_classes`` keys off ``DeviceExecutionError.failure_class``
    (utils/errors.FAILURE_CLASSES): only 'unavailable' is retriable as-is;
    'oom' needs a cheaper configuration (the fallback chain's
    reduced-precision move, resilience/fallback.py), and 'callback' /
    'unsupported' cannot succeed on retry at all.
    """
    max_attempts: int = 3
    base_delay: float = 0.5
    backoff_factor: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.0
    jitter_seed: int = 0
    retriable_classes: tuple = ("unavailable",)
    sleep: object = time.sleep     # injectable for tests (recorded delays)

    def delay(self, retry_index: int) -> float:
        """Backoff before retry ``retry_index`` (0-based)."""
        d = min(self.base_delay * self.backoff_factor ** retry_index,
                self.max_delay)
        if self.jitter:
            import random
            rng = random.Random((self.jitter_seed, retry_index))
            d *= 1.0 + self.jitter * rng.random()
        return d

    def should_retry(self, exc: Exception) -> bool:
        return (isinstance(exc, DeviceExecutionError)
                and exc.failure_class in self.retriable_classes)


def default_checkpoint_path(ksp=None) -> str:
    """Default solve-state checkpoint path, unique per process AND per
    solver object — concurrent resilient solves in one process must never
    restore each other's operators from a shared file."""
    tag = f"_{id(ksp):x}" if ksp is not None else ""
    return os.path.join(tempfile.gettempdir(),
                        f"tpu_solve_ckpt_{os.getpid()}{tag}.npz")


def resilient_solve(ksp, b, x, policy: RetryPolicy | None = None, *,
                    checkpoint_path: str | None = None) -> SolveResult:
    """``ksp.solve(b, x)`` that survives retriable device failures.

    On a retriable ``DeviceExecutionError`` (per ``policy``), checkpoints
    the best iterate, backs off, rebuilds the operators from the
    checkpoint, and resumes from the restored iterate — up to
    ``policy.max_attempts`` total attempts. Non-retriable failures and
    exhausted policies re-raise the original error.

    ``checkpoint_path`` defaults to :func:`default_checkpoint_path`.
    Matrix-free operators (no ``to_scipy``) skip persistence — the retry
    still resumes from the in-memory iterate.

    Returns the converged attempt's :class:`SolveResult` with ``attempts``
    and the ``recovery_events`` trail filled in.
    """
    policy = policy or RetryPolicy()
    path = checkpoint_path or default_checkpoint_path(ksp)
    events: list[RecoveryEvent] = []
    guess_flag0 = ksp._initial_guess_nonzero
    attempt = 1
    try:
        while True:
            try:
                result = ksp.solve(b, x)
                break
            except DeviceExecutionError as exc:
                if (attempt >= policy.max_attempts
                        or not policy.should_retry(exc)):
                    raise
                events.append(RecoveryEvent(
                    kind="fault", attempt=attempt, detail=str(exc),
                    error_class=exc.failure_class))
                mat = ksp.get_operators()[0]
                persisted = hasattr(mat, "to_scipy")
                if persisted:
                    save_solve_state(path, mat, x, b, iteration=0)
                    events.append(RecoveryEvent(
                        kind="checkpoint", attempt=attempt, detail=path))
                delay = policy.delay(attempt - 1)
                events.append(RecoveryEvent(
                    kind="backoff", attempt=attempt, delay=delay,
                    error_class=exc.failure_class))
                policy.sleep(delay)
                if persisted:
                    # rebuild from the checkpoint: fresh device buffers
                    # (nothing from before the failure is trusted), iterate
                    # restored onto the CALLER's vector so x stays live
                    mat2, x2, _b2, _it = load_solve_state(path, mat.comm)
                    ksp.set_operators(mat2)
                    x.data = x2.data
                ksp.set_initial_guess_nonzero(True)
                attempt += 1
                events.append(RecoveryEvent(
                    kind="resume", attempt=attempt,
                    detail="initial_guess_nonzero from restored iterate"))
    finally:
        ksp.set_initial_guess_nonzero(guess_flag0)
    result.attempts = attempt
    result.recovery_events = events
    return result


def resilient_solve_many(ksp, B, X=None, policy: RetryPolicy | None = None,
                         *, checkpoint_path: str | None = None
                         ) -> BatchedSolveResult:
    """``ksp.solve_many(B, X)`` that survives retriable device failures —
    the batched twin of :func:`resilient_solve`.

    The checkpoint carries the whole ``(n, nrhs)`` iterate/RHS blocks
    (:func:`utils.checkpoint.save_solve_state_many`): a mid-batch crash
    leaves the partial iterate BLOCK in ``X`` (the ``ksp.program`` fault
    boundary in KSP.solve_many writes it before raising), the rebuilt
    solve resumes every column from where it froze, and already-converged
    columns re-converge in O(1) iterations under the masked-convergence
    kernel. Same zero-overhead contract: no failure means exactly one
    ``ksp.solve_many``.
    """
    import numpy as np
    policy = policy or RetryPolicy()
    path = checkpoint_path or default_checkpoint_path(ksp)
    events: list[RecoveryEvent] = []
    guess_flag0 = ksp._initial_guess_nonzero
    mat = ksp.get_operators()[0]
    if isinstance(B, (list, tuple)):
        # the same Vec-stacking normalization KSP.solve_many accepts —
        # a bare asarray would mangle a list of Vecs into an object array
        B = np.stack([b.to_numpy() if hasattr(b, "to_numpy")
                      else np.asarray(b) for b in B], axis=1)
    B = np.asarray(B)
    if X is None:
        X = np.zeros(B.shape, dtype=np.dtype(mat.dtype))
    else:
        # the wrapper's resume contract needs a WRITABLE host ndarray the
        # fault boundary writes the partial iterate into — a jax array
        # (asarray of one is a read-only view) or nested list would make
        # solve_many checkpoint the stale guess or die on the in-place
        # restore below
        X = np.asarray(X)
        if not X.flags.writeable:
            X = X.copy()
    attempt = 1
    try:
        while True:
            try:
                result = ksp.solve_many(B, X)
                break
            except DeviceExecutionError as exc:
                if (attempt >= policy.max_attempts
                        or not policy.should_retry(exc)):
                    raise
                events.append(RecoveryEvent(
                    kind="fault", attempt=attempt, detail=str(exc),
                    error_class=exc.failure_class))
                mat = ksp.get_operators()[0]
                persisted = hasattr(mat, "to_scipy")
                if persisted:
                    save_solve_state_many(path, mat, X, B, iteration=0)
                    events.append(RecoveryEvent(
                        kind="checkpoint", attempt=attempt, detail=path))
                delay = policy.delay(attempt - 1)
                events.append(RecoveryEvent(
                    kind="backoff", attempt=attempt, delay=delay,
                    error_class=exc.failure_class))
                policy.sleep(delay)
                if persisted:
                    mat2, X2, _B2, _it = load_solve_state_many(path,
                                                               mat.comm)
                    ksp.set_operators(mat2)
                    X[...] = X2.astype(X.dtype, copy=False)
                ksp.set_initial_guess_nonzero(True)
                attempt += 1
                events.append(RecoveryEvent(
                    kind="resume", attempt=attempt,
                    detail="initial_guess_nonzero from restored "
                           "iterate block"))
    finally:
        ksp.set_initial_guess_nonzero(guess_flag0)
    result.attempts = attempt
    result.recovery_events = events
    return result
