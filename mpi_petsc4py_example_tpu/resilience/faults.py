"""Deterministic fault injection at the framework's solve boundaries.

The resilience layer (retry/backoff, checkpoint-resume, fallback chains)
is only trustworthy if its recovery paths can be EXERCISED — so the
framework carries named fault points at its solve and communication
boundaries that synthetic, reproducible faults can be attached to:

* raise ``XlaRuntimeError``-shaped device failures (``unavailable``: the
  worker-crash signature; ``oom``: RESOURCE_EXHAUSTED) exactly where real
  ones surface, so :func:`utils.errors.wrap_device_errors` classifies them
  identically;
* poison a solve's residual with NaN/Inf "at iteration k" (``nan``/``inf``
  at ``ksp.result`` — the DIVERGED_NANORINF / fallback-chain trigger);
* drop or corrupt a collective (``comm.psum`` at trace time, ``comm.fetch``
  / ``comm.put`` at the host boundary);
* SILENTLY corrupt an in-program operator or preconditioner apply
  (``spmv.result`` / ``pc.apply``, trace time: ``bitflip``/``scale`` —
  no crash, no NaN; the corruption the ABFT checksums and invariant
  monitors in resilience/abft.py + solvers/krylov.py must detect);
* PERMANENTLY lose a device (``device.lost``): unlike the hit-count
  one-shots above, a fired loss is STICKY — the device goes into a
  per-process lost registry and every later solve or placement touching
  a mesh that contains it fails with the ``unavailable`` signature,
  until :func:`heal` clears it. This is the persistent-failure model
  the elastic degraded-mesh escalation (resilience/elastic.py +
  retry.py ``mesh_shrink``) recovers from: same-mesh retries CANNOT
  succeed, only excluding the device can.

Activation — spec string via either route::

    with inject_faults("ksp.program=unavailable:iter=5"):
        resilient_solve(ksp, b, x)                 # context manager

    TPU_SOLVE_FAULTS="ksp.solve=oom" python driver.py   # environment

Spec grammar (comma-separated clauses)::

    clause := point '=' kind (':' param '=' value)*
    point  := one of FAULT_POINTS
    kind   := unavailable | oom | nan | inf | drop | corrupt
            | bitflip | scale                  (silent corruption)
            | delay | partition                (timing / stale exchange)
            | duplicate | reorder              (rpc.* delivery faults)
    params := at=N      trigger on the Nth hit of the point (default 1)
              device=D  device id to lose ('device.lost' clauses; default:
                        the highest device id in the checked mesh) — or
                        the device/block a 'delay'/'partition' clause
                        targets (default: every device)
              mag=M     relative error of 'scale' corruption (default 1e-3)
              mean=T    mean injected latency in seconds ('comm.delay'
                        clauses; with seed= the delay is drawn
                        exponential(mean), else exactly T; default 0.01)
              times=M   stay armed for M consecutive hits ('*' = forever)
              iter=K    simulated crash/poison iteration (ksp.program /
                        ksp.result: the partial iterate of K real device
                        iterations survives, as after a worker crash)
              seed=S    seeded schedule: instead of at/times, each hit
              prob=P    fires independently with probability P drawn from
                        random.Random(S) — reproducible across runs

Every fault is deterministic: hit counters and seeded RNG streams are
per-clause, so a test that injects ``at=2:times=1`` sees exactly the
second hit fail and nothing else, every run. With no spec active every
fault point is a near-no-op (one module attribute check — zero device
work, zero extra XLA programs).

This module is stdlib-only and imported by ``parallel/mesh.py`` — it must
never import jax or other framework modules.
"""

from __future__ import annotations

import contextlib
import os
import random
import re
import threading

# Registry of named fault points and the fault kinds each supports.
# tpslint TPS012 checks call sites against this.
FAULT_POINTS = {
    "ksp.solve":   ("unavailable", "oom"),   # KSP.solve entry (all paths)
    "ksp.program": ("unavailable", "oom"),   # around the compiled solve
    "ksp.result":  ("nan", "inf"),           # poison the fetched residual
    "eps.solve":   ("unavailable", "oom"),   # EPS.solve entry
    "comm.put":    ("unavailable", "oom"),   # device_put data placement
    "comm.fetch":  ("unavailable", "drop", "corrupt"),  # host gather
    "comm.psum":   ("drop", "corrupt"),      # traced in-program collective
    # SILENT data corruption (no crash, no NaN): applied at TRACE time to
    # the operator/preconditioner apply inside the compiled solve, so the
    # corruption bakes into every execution of that program — the SDC
    # model the ABFT/monitor layer (resilience/abft.py) must catch.
    # 'bitflip' flips a high exponent bit of one element (a localized,
    # huge error); 'scale' multiplies the whole result by (1 + mag) (a
    # systematic small relative error — mag= spec param, default 1e-3).
    # Hit counters advance once per TRACED apply site (init residual,
    # loop body, replacement branch, ...), so at=N selects WHICH site of
    # the program is corrupted; a clause that is spent no longer forces
    # cache isolation and retries get a clean program (trace_key()).
    "spmv.result": ("bitflip", "scale"),     # operator apply, in-program
    "pc.apply":    ("bitflip", "scale"),     # PC apply, in-program
    # PERSISTENT device loss (sticky until heal()): a fired clause marks
    # its device= in the module's lost registry; solves and placements on
    # meshes containing a lost device keep failing 'unavailable' until
    # faults.heal() — or until the elastic layer rebuilds onto a smaller
    # mesh that excludes it (resilience/elastic.py). Hit counters advance
    # once per SOLVE-PROGRAM boundary on a mesh containing the device
    # (solvers/ksp.py mesh_fault site), so at=N picks the Nth solve and
    # iter=K leaves K iterations of real partial state, like ksp.program.
    "device.lost": ("unavailable",),         # permanent worker/chip loss
    # TIMING faults (the first in the registry): 'comm.delay' injects
    # per-device latency into host-side communication paths — the async
    # multisplitting tier (solvers/multisplit.py) sleeps the returned
    # seconds before publishing a boundary exchange, which is how a slow
    # or jittery device is SIMULATED rather than crashed. 'delay' with
    # device=D + times=* is a sticky slow device; seed=S draws
    # reproducible exponential jitter around mean= (seconds) instead of
    # a fixed delay. Consumed via delay_seconds(), never check().
    "comm.delay":  ("delay",),               # per-device latency jitter
    # Stale-exchange boundary (parallel/exchange.py StaleExchange):
    # 'drop' discards one publish (the reader keeps serving the previous
    # version — staleness grows by one); 'partition' with device=D
    # discards every publish FROM block/device D while armed (times=* =
    # a partitioned peer), the network-split model the bounded-staleness
    # supervisor must resync or degrade around.
    "exchange.put": ("drop", "partition"),   # stale-exchange publish
    # RPC transport boundaries (serving/transport.py): 'rpc.send' is
    # the CLIENT send side (device= is the destination host index) —
    # 'drop' loses the request in flight (the client's per-attempt
    # timeout fires and the retry tier re-sends under the SAME
    # idempotency key), 'duplicate' delivers the request twice (the
    # host-side idempotency cache must collapse them to one execution),
    # 'delay'/'reorder' hold the message (reorder long enough for a
    # concurrent later message to overtake — non-FIFO delivery), and
    # 'partition' with device=H:times=* makes host H unreachable while
    # armed (the network-split model the epoch-numbered placement
    # reconcile heals without split-braining). 'rpc.recv' is the HOST
    # side, applied AFTER the handler ran and BEFORE the reply leaves:
    # a 'drop'/'partition' here means the work WAS done but the client
    # never hears — the canonical duplicate-generating failure the
    # idempotent-retry contract exists for.
    "rpc.send": ("drop", "delay", "duplicate", "reorder", "partition"),
    "rpc.recv": ("drop", "delay", "duplicate", "reorder", "partition"),
}

RAISING_KINDS = ("unavailable", "oom")

_KIND_MESSAGES = {
    "unavailable": ("UNAVAILABLE: TPU worker process crashed (injected "
                    "fault at {point!r})"),
    "oom": ("RESOURCE_EXHAUSTED: Out of memory while running program "
            "(injected fault at {point!r})"),
}


class XlaRuntimeError(RuntimeError):
    """Synthetic device failure. Deliberately NAMED like the real jaxlib
    error so :func:`utils.errors.wrap_device_errors` — which classifies by
    type NAME, never by type identity — wraps injected faults through the
    exact code path real device failures take."""


class FaultSpecError(ValueError):
    """A malformed ``TPU_SOLVE_FAULTS`` / ``inject_faults`` spec."""


class Fault:
    """One parsed fault clause with its own deterministic trigger state."""

    def __init__(self, point: str, kind: str, at: int = 1, times: int = 1,
                 forever: bool = False, iter_k: int | None = None,
                 seed: int | None = None, prob: float = 1.0,
                 mag: float = 1e-3, device: int | None = None,
                 mean: float = 0.01):
        self.point = point
        self.kind = kind
        self.at = at
        self.times = times
        self.forever = forever
        self.iter_k = iter_k
        self.prob = prob
        self.mag = mag       # relative magnitude of 'scale' corruption
        self.mean = mean     # mean latency in seconds ('delay' clauses)
        self.device = device  # device id (device.lost/delay/partition)
        self._rng = random.Random(seed) if seed is not None else None
        self.hits = 0      # times the point was reached
        self.fired = 0     # times this fault actually triggered

    def check(self) -> bool:
        """Count one hit of the point; True when the fault triggers."""
        self.hits += 1
        if self._rng is not None:
            fire = self._rng.random() < self.prob
        else:
            fire = (self.hits >= self.at
                    and (self.forever or self.hits < self.at + self.times))
        if fire:
            self.fired += 1
        return fire

    def spent(self) -> bool:
        """True when no FUTURE hit can fire (counter window passed).
        Seeded and ``times=*`` schedules are never spent."""
        return (self._rng is None and not self.forever
                and self.hits >= self.at + self.times - 1)

    def error(self) -> XlaRuntimeError:
        self.flight_record()
        msg = _KIND_MESSAGES[self.kind].format(point=self.point)
        if self.device is not None:
            # name the device: HealthMonitor attributes repeated failures
            # by parsing this (real runtimes name failing chips too)
            msg += (f"; device {self.device} is LOST — persistent until "
                    "faults.heal() or a mesh rebuild excludes it")
        err = XlaRuntimeError(msg)
        # iter=K clauses leave K iterations of real partial state in the
        # caller's iterate; carry that so the resilience layer checkpoints
        # the true progress (retry.py records/resumes the iteration)
        err.iteration = int(self.iter_k or 0)
        return err

    def flight_record(self):
        """Record this fault into the telemetry flight recorder (every
        registered fault point has an event site — the coverage contract
        ``telemetry/names.FLIGHT_FAULT_POINTS`` declares and tpslint
        TPS014 enforces). Lazy + guarded: this module must stay
        importable without the telemetry package (stdlib-only contract),
        and recording must never mask the fault itself."""
        try:
            from ..telemetry import flight as _flight
        except ImportError:
            return
        _flight.record_fault(self.point, self.kind, device=self.device,
                             iteration=int(self.iter_k or 0),
                             hits=self.hits)

    def __repr__(self):
        sched = (f"seed prob={self.prob}" if self._rng is not None else
                 f"at={self.at} times={'*' if self.forever else self.times}")
        return (f"Fault({self.point}={self.kind}, {sched}, "
                f"hits={self.hits}, fired={self.fired})")


def _parse_clause(clause: str) -> Fault:
    head, _, tail = clause.partition(":")
    point, eq, kind = head.partition("=")
    point, kind = point.strip(), kind.strip()
    if not eq or not point or not kind:
        raise FaultSpecError(
            f"fault clause {clause!r}: expected '<point>=<kind>[:k=v...]'")
    if point not in FAULT_POINTS:
        raise FaultSpecError(
            f"unknown fault point {point!r}; known: {sorted(FAULT_POINTS)}")
    if kind not in FAULT_POINTS[point]:
        raise FaultSpecError(
            f"fault point {point!r} supports kinds {FAULT_POINTS[point]}, "
            f"not {kind!r}")
    kw = {}
    for param in filter(None, (p.strip() for p in tail.split(":"))):
        key, eq, value = param.partition("=")
        if not eq:
            raise FaultSpecError(
                f"fault clause {clause!r}: parameter {param!r} is not "
                "'key=value'")
        try:
            if key == "at":
                kw["at"] = int(value)
            elif key == "times":
                if value == "*":
                    kw["forever"] = True
                else:
                    kw["times"] = int(value)
            elif key == "iter":
                kw["iter_k"] = int(value)
            elif key == "seed":
                kw["seed"] = int(value)
            elif key == "prob":
                kw["prob"] = float(value)
            elif key == "mag":
                kw["mag"] = float(value)
            elif key == "mean":
                kw["mean"] = float(value)
            elif key == "device":
                kw["device"] = int(value)
            else:
                raise FaultSpecError(
                    f"fault clause {clause!r}: unknown parameter {key!r} "
                    "(have: at, times, iter, seed, prob, mag, mean, "
                    "device)")
        except ValueError as e:
            if isinstance(e, FaultSpecError):
                raise
            raise FaultSpecError(
                f"fault clause {clause!r}: bad value for {key!r}: {e}") from e
    if "prob" in kw and "seed" not in kw:
        raise FaultSpecError(
            f"fault clause {clause!r}: prob= needs seed= (schedules must "
            "be reproducible)")
    return Fault(point, kind, **kw)


def parse_spec(spec: str) -> list[Fault]:
    """Parse a full fault spec into armed :class:`Fault` clauses."""
    faults = [_parse_clause(c.strip())
              for c in spec.split(",") if c.strip()]
    if not faults:
        raise FaultSpecError(f"empty fault spec {spec!r}")
    return faults


# ---- active plan ----------------------------------------------------------
# _UNSET: the env var has not been consulted yet. None: no faults active.
_UNSET = object()
_PLAN = _UNSET
_LOCK = threading.Lock()
_TRACE_NONCE = 0


def _active_plan():
    global _PLAN
    if _PLAN is _UNSET:
        with _LOCK:
            if _PLAN is _UNSET:
                spec = os.environ.get("TPU_SOLVE_FAULTS", "").strip()
                _PLAN = parse_spec(spec) if spec else None
    return _PLAN


def active() -> bool:
    """Whether any fault plan is armed (env var or context manager)."""
    return _active_plan() is not None


def reset():
    """Forget the cached env-var plan (re-read on next fault-point hit)."""
    global _PLAN
    with _LOCK:
        _PLAN = _UNSET


@contextlib.contextmanager
def inject_faults(spec: str):
    """Arm a fault plan for the duration of the block (replaces any
    env-var plan; restores it after). Yields the parsed fault list so
    tests can assert on ``hits``/``fired`` counters."""
    global _PLAN
    plan = parse_spec(spec)
    with _LOCK:
        saved, _PLAN = _PLAN, plan
    try:
        yield plan
    finally:
        with _LOCK:
            _PLAN = saved


def triggered(point: str, device: int | None = None):
    """Hot-path hook: count a hit of ``point`` against the active plan.

    Returns the :class:`Fault` that fired (the call site applies its
    effect — raise, poison, drop) or None. Near-no-op when no plan is
    armed. ``device`` identifies WHO hit the point (the publishing
    block/device id at ``exchange.put``): a clause carrying ``device=D``
    then only counts — and only fires — for that id, the sticky
    partitioned-peer model; clauses without ``device=`` match everyone.
    """
    plan = _active_plan()
    if plan is None:
        return None
    with _LOCK:
        fired = None
        for fault in plan:
            if fault.point != point:
                continue
            if (device is not None and fault.device is not None
                    and fault.device != int(device)):
                continue
            if fault.check():
                fired = fault
                break
    if fired is not None and fired.kind not in RAISING_KINDS:
        # non-raising kinds (nan/inf poison, drops, silent corruption)
        # never reach Fault.error() — record their flight event here;
        # raising kinds record once inside error() itself
        fired.flight_record()
    return fired


def check(point: str):
    """Raising-kind fault points: raise the synthetic device error if a
    fault fires at ``point`` (no-op otherwise)."""
    fault = triggered(point)
    if fault is not None and fault.kind in RAISING_KINDS:
        raise fault.error()


def delay_seconds(point: str, device: int | None = None) -> float:
    """Hot-path hook for TIMING fault points (``comm.delay``): seconds
    of injected latency the caller must sleep before its communication
    step — 0.0 with no armed delay clause (near-no-op, like
    :func:`triggered`).

    ``device`` is the id doing the communicating; a clause with
    ``device=D:times=*`` is a STICKY slow device (only D's hits count,
    every one fires), the straggler model asynchronous multisplitting
    (solvers/multisplit.py) is built to absorb. A seeded clause draws
    each delay from an exponential distribution with mean ``mean=``
    seconds (``random.Random(seed).expovariate`` — reproducible jitter);
    an unseeded clause injects exactly ``mean`` seconds. Hit windows
    (``at``/``times``/``prob``) gate each draw like any other fault.
    Multiple matching clauses add up.
    """
    plan = _active_plan()
    if plan is None:
        return 0.0
    total = 0.0
    fired = []
    with _LOCK:
        for fault in plan:
            if fault.point != point or fault.kind != "delay":
                continue
            if (device is not None and fault.device is not None
                    and fault.device != int(device)):
                continue
            if not fault.check():
                continue
            if fault._rng is not None and fault.mean > 0:
                total += fault._rng.expovariate(1.0 / fault.mean)
            else:
                total += max(0.0, fault.mean)
            fired.append(fault)
    for fault in fired:
        fault.flight_record()
    return total


# fault points whose effect applies while a program is being TRACED (and
# therefore bakes into the compiled artifact, demanding cache isolation)
TRACE_TIME_POINTS = ("comm.psum", "spmv.result", "pc.apply")


def trace_key():
    """Cache-key token for compiled-program caches (krylov._PROGRAM_CACHE).

    None when no plan is armed — keys, and therefore program reuse, are
    byte-identical to a fault-free build. None ALSO when the armed plan
    has no live trace-time fault (host-boundary kinds like ``ksp.result``,
    or a ``comm.psum`` clause whose trigger window has passed): those
    cannot bake into a jaxpr, so a long-running driver under
    ``TPU_SOLVE_FAULTS`` keeps normal program caching. Otherwise a fresh
    nonce per call: a program traced while a collective fault could fire
    must never be cached-shared with — or survive into — fault-free
    solves.
    """
    global _TRACE_NONCE
    plan = _active_plan()
    if plan is None:
        return None
    with _LOCK:
        if not any(f.point in TRACE_TIME_POINTS and not f.spent()
                   for f in plan):
            return None
        _TRACE_NONCE += 1
        return _TRACE_NONCE


# ---- persistent device loss ----------------------------------------------
# Unlike the hit-count one-shots, a lost device is STICKY process state:
# device id -> description, populated by a fired 'device.lost' clause or
# mark_lost(), cleared only by heal(). Every solve-program boundary and
# data placement consults it, so a mesh containing a lost device keeps
# failing 'unavailable' — the failure model where same-mesh retries are
# futile and only the elastic shrink (resilience/elastic.py) helps.
_LOST: dict[int, str] = {}

# Monotonic heal generation: bumped by every heal() that actually cleared
# a lost mark. Consumers that want to react to 'hardware came back'
# (the elastic RE-GROW path — resilience/elastic.py ladder-up, the
# SolveServer's degraded-capacity recovery) poll this instead of the
# registry itself: an empty registry cannot distinguish 'never lost'
# from 'lost and repaired', the epoch can.
_HEAL_EPOCH = 0


def lost_devices() -> frozenset:
    """Device ids currently marked lost (sticky until :func:`heal`)."""
    with _LOCK:
        return frozenset(_LOST)


def mark_lost(device_id: int, reason: str = "marked via faults.mark_lost"):
    """Mark a device as persistently lost (the programmatic route — a
    health monitor that classified real repeated failures uses this)."""
    with _LOCK:
        _LOST[int(device_id)] = str(reason)


def heal(device_id: int | None = None) -> tuple:
    """Clear the lost mark from one device (or all, when ``device_id`` is
    None) — the explicit 'hardware was replaced/repaired' signal. Returns
    the ids that were healed. A heal that actually cleared something
    bumps the process heal epoch (:func:`heal_epoch`) — the signal the
    elastic RE-GROW ladder (resilience/elastic.py + serving) keys on."""
    global _HEAL_EPOCH
    with _LOCK:
        if device_id is None:
            healed = tuple(sorted(_LOST))
            _LOST.clear()
        else:
            healed = ((int(device_id),)
                      if _LOST.pop(int(device_id), None) is not None
                      else ())
        if healed:
            _HEAL_EPOCH += 1
        return healed


def heal_epoch() -> int:
    """Monotonic count of effective :func:`heal` calls this process.
    Cheap to poll (one lock acquisition, no device work): the
    HealthMonitor and the serving layer compare it against a remembered
    value to detect 'devices came back since I last looked' without
    scanning device state."""
    with _LOCK:
        return _HEAL_EPOCH


def check_lost(device_ids):
    """Raise the 'unavailable' loss error if any of ``device_ids`` is in
    the sticky lost registry. Registry-only (never consumes armed
    clauses) — the placement-boundary guard (parallel/mesh.py), so data
    cannot be placed onto a mesh containing a lost device."""
    if not _LOST:               # lock-free fast path: empty registry
        return
    with _LOCK:
        down = sorted(d for d in device_ids if d in _LOST)
    if down:
        raise Fault("device.lost", "unavailable", device=down[0]).error()


def mesh_fault(point, device_ids):
    """Hot-path hook for the solve-program boundary (solvers/ksp.py):
    returns the :class:`Fault` to apply when the mesh over ``device_ids``
    has (or just) lost a device, else None.

    Two routes produce a fault: an armed ``device.lost`` clause whose
    device is in the mesh fires (counting one hit per call — at=N picks
    the Nth solve; the device goes into the sticky registry, and the
    returned clause may carry ``iter=K`` partial-progress semantics), or
    the registry already holds a mesh member (every later solve fails
    until heal()/shrink). Near-no-op with no plan and an empty registry.
    """
    plan = _active_plan()
    if plan is None and not _LOST:
        return None
    ids = tuple(int(i) for i in device_ids)
    fired = None
    if plan is not None:
        with _LOCK:
            for fault in plan:
                if fault.point != point:
                    continue
                dev = fault.device
                if dev is None:
                    dev = max(ids) if ids else 0
                if dev not in ids:
                    continue
                if fault.check():
                    fault.device = dev
                    _LOST[dev] = f"injected {point}={fault.kind}"
                    if fired is None:
                        fired = fault
    if fired is not None:
        return fired
    with _LOCK:
        down = sorted(d for d in ids if d in _LOST)
    if down:
        return Fault(point, "unavailable", device=down[0])
    return None


# ---- health monitoring ----------------------------------------------------
_DEVICE_ID_RE = re.compile(r"device\s+(\d+)", re.IGNORECASE)


def device_from_error(exc) -> int | None:
    """Device id named by a failure, or None when unattributable. Looks
    at the ORIGINAL runtime error when the exception is a classified
    wrapper (utils.errors.DeviceExecutionError keeps it on
    ``.original``) — the wrapper's own message is the hint, not the
    device-naming runtime text."""
    msg = str(getattr(exc, "original", None) or exc)
    m = _DEVICE_ID_RE.search(msg)
    return int(m.group(1)) if m else None


class HealthMonitor:
    """Classifies repeated ``unavailable`` failures as persistent loss.

    A transient worker crash recovers after one backoff; a device that
    keeps failing is GONE and waiting on it is futile. The monitor
    counts consecutive unavailable failures per attributed device (or
    per mesh, when the error names no device); once a device reaches
    ``threshold`` it is classified lost (:meth:`lost_devices` — the set
    the elastic MeshRebuilder excludes), and :meth:`persistent` reports
    when same-mesh retrying has used up its evidence either way. A
    successful solve calls :meth:`healthy` — the evidence is
    consecutive-failure evidence, success resets it.
    """

    def __init__(self, threshold: int = 2):
        self.threshold = max(1, int(threshold))
        self._counts: dict = {}       # device id (or None) -> failures
        self.failures = 0             # total recorded since last healthy()
        self._heal_epoch = heal_epoch()   # heal generation last observed

    def record(self, exc) -> int | None:
        """Count one unavailable failure; returns the attributed device
        id (None when the error names no device)."""
        dev = device_from_error(exc)
        self.failures += 1
        self._counts[dev] = self._counts.get(dev, 0) + 1
        return dev

    def healthy(self):
        """A solve succeeded on the current mesh: reset the evidence."""
        self._counts.clear()
        self.failures = 0

    def lost_devices(self) -> frozenset:
        """Devices classified lost: attributed failure count reached the
        threshold."""
        return frozenset(d for d, c in self._counts.items()
                         if d is not None and c >= self.threshold)

    def persistent(self) -> bool:
        """True once ANY attribution (a device, or the unattributed mesh
        bucket) has failed ``threshold`` times — the same-mesh-retries-
        are-futile classification that triggers the shrink escalation."""
        return any(c >= self.threshold for c in self._counts.values())

    def heal_observed(self) -> bool:
        """True when :func:`heal` cleared a lost device since this
        monitor was constructed (or since this method last returned
        True) — the classification that turns the elastic ladder UPWARD:
        a previously shrunk session may re-grow onto the repaired
        hardware (resilience/elastic.MeshRebuilder.grown_comm). The
        observation is consuming, like the failure evidence: one heal
        triggers one re-grow attempt, not a re-grow per retry."""
        ep = heal_epoch()
        if ep != self._heal_epoch:
            self._heal_epoch = ep
            return True
        return False

    def __repr__(self):
        return (f"HealthMonitor(threshold={self.threshold}, "
                f"counts={self._counts})")
