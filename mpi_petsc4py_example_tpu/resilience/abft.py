"""Algorithm-based fault tolerance (ABFT) for the SpMV/PC apply path.

Silent data corruption — a flipped bit in an SpMV result, a corrupted
psum, a mis-scaled preconditioner apply — produces no crash and no NaN:
without a detector the Krylov recurrence happily reports CONVERGED over a
wrong iterate (the ``faults.py`` silent kinds ``spmv.result``/``pc.apply``
reproduce this deterministically). The classic Krylov answer (Huang &
Abraham's checksum ABFT, plus periodic residual replacement) maps onto
this framework's fused-reduction structure with ZERO extra collectives:

* **column checksum**: precompute ``c = Aᵀ·1`` per operator format
  (ELL/DIA host CSR, device-only ELL shards, analytic for the matrix-free
  stencil) ONCE on the host, independently of the device apply — the
  identity ``⟨1, A x⟩ = ⟨c, x⟩`` then verifies every in-program apply.
  The two sides are local partial sums folded into the SAME stacked
  ``psum`` that already reduces ``⟨p, A p⟩`` (solvers/krylov.py guarded
  kernels), so the per-iteration collective COUNT does not grow;
* **PC checksum**: the same identity for preconditioner applies,
  ``c_M = Mᵀ·1``, available for the kinds whose operator form is known at
  setup (none/jacobi — :func:`pc_checksum` returns None otherwise and the
  M-channel check is skipped);
* **dtype-aware tolerance**: both checksum sums are tree reductions, so
  their benign rounding is O(log2(n) · eps) relative to the ABSOLUTE sums
  ``Σ|y|`` / ``Σ|c⊙x|`` (folded into the same psum); the detector fires on
  ``|⟨1,y⟩ - ⟨c,x⟩| > tol_factor · eps · scale`` with ``tol_factor``
  runtime-tunable (``-ksp_abft_tol``, default 256 — comfortably above
  tree-reduction rounding at any practical n, far below any corruption
  worth the name).

This module also owns the TRACE-TIME corruption applicator for the silent
fault kinds (``faults.py`` stays stdlib-only and cannot touch jnp).
"""

from __future__ import annotations

import numpy as np

from . import faults as _faults

#: default ``-ksp_abft_tol`` multiplier: threshold = tol * eps * scale
DEFAULT_ABFT_TOL = 256.0


# ---------------------------------------------------------------------------
# trace-time silent corruption (the spmv.result / pc.apply fault kinds)
# ---------------------------------------------------------------------------

def _bitflip(y):
    """Flip a high exponent bit of element 0 — one localized, huge error
    (the single-event-upset model). Bitcast for real floats; complex
    dtypes corrupt by sign+magnitude instead (no complex bitcast).

    A ZERO word needs its own arm: the exponent-bit flip of 0.0 lands at
    a denormal-scale value (2^-63 for f32) and ``x * -3`` keeps 0 at 0,
    so a clause whose ``at=`` selected an apply of an all-zero operand —
    the ``at=1`` init-residual site ``r = b - A(x0)`` under the default
    zero guess — historically injected NOTHING and the one-shot window
    was spent without a detectable fault ever firing. A real upset on a
    zero word is as physical as any other, so zeros corrupt to unit
    scale instead (regression: tests/test_resilience.py)."""
    import jax.numpy as jnp
    from jax import lax
    flat = y.ravel()
    if jnp.issubdtype(y.dtype, jnp.complexfloating):
        hit = jnp.where(flat[0] == 0, jnp.asarray(1.0, y.dtype),
                        flat[0] * -3.0)
        flat = flat.at[0].set(hit)
    else:
        ibits = {2: jnp.int16, 4: jnp.int32, 8: jnp.int64}[y.dtype.itemsize]
        bit = {2: 1 << 13, 4: 1 << 29, 8: 1 << 61}[y.dtype.itemsize]
        as_int = lax.bitcast_convert_type(flat, ibits)
        as_int = as_int.at[0].set(as_int[0] ^ bit)
        flipped = lax.bitcast_convert_type(as_int, y.dtype)
        flat = flipped.at[0].set(
            jnp.where(flat[0] == 0, jnp.asarray(1.0, y.dtype), flipped[0]))
    return flat.reshape(y.shape)


def apply_silent_fault(point: str, y):
    """Consult the armed fault plan at TRACE time; if a silent fault fires
    at ``point``, return the corrupted array (the corruption bakes into
    the jaxpr — every execution of the traced program carries it).
    Program caches are isolated via ``faults.trace_key()`` exactly like
    ``comm.psum`` (solvers/krylov.py cache keys)."""
    fault = _faults.triggered(point)
    if fault is None:
        return y
    if fault.kind == "bitflip":
        return _bitflip(y)
    if fault.kind == "scale":
        return y * (1.0 + fault.mag)
    return y


# ---------------------------------------------------------------------------
# column checksums, per operator format
# ---------------------------------------------------------------------------

def column_checksum(operator) -> np.ndarray:
    """The ABFT column-checksum vector ``c = Aᵀ·1`` (global, host-side).

    Computed INDEPENDENTLY of the device apply — from the host CSR when
    retained, from the fetched ELL shards otherwise, analytically for the
    matrix-free stencil — so a corrupted device channel can never produce
    a self-consistently corrupted checksum. Cached on the operator keyed
    by its mutation counter (``Mat._state``).
    """
    state = getattr(operator, "_state", 0)
    cached = getattr(operator, "_abft_checksum", None)
    if cached is not None and cached[0] == state:
        return cached[1]
    c = _compute_checksum(operator)
    try:
        operator._abft_checksum = (state, c)
    except AttributeError:    # operators with __slots__: skip the cache
        pass
    return c


def _compute_checksum(operator) -> np.ndarray:
    own = getattr(operator, "column_checksum_host", None)
    if own is not None:                    # operator-provided (stencil)
        return np.asarray(own())
    from ..utils.dtypes import host_dtype, is_low_precision

    def _acc_dt(values):
        # low-precision storage (bf16) must not ACCUMULATE the checksum
        # in itself — the setup-time sum runs in host fp64 (the caller
        # casts the placed vector back to the storage dtype; the bf16
        # rounding of the finished sum is covered by the storage-eps
        # threshold, the bf16 rounding of every PARTIAL sum would not be)
        dt = np.asarray(values).dtype
        return host_dtype(dt) if is_low_precision(dt) else dt

    n = operator.shape[1]
    host_csr = getattr(operator, "host_csr", None)
    if host_csr is not None:
        indptr, indices, data = host_csr
        c = np.zeros(n, dtype=_acc_dt(data))
        np.add.at(c, np.asarray(indices),
                  np.asarray(data).astype(c.dtype, copy=False))
        return c
    # device-only ELL shards: fetch once (setup-time, host-side)
    cols = operator.comm.host_fetch(operator.ell_cols)[: operator.shape[0]]
    vals = operator.comm.host_fetch(operator.ell_vals)[: operator.shape[0]]
    c = np.zeros(n, dtype=_acc_dt(vals))
    # padding slots are (col 0, val 0.0) — they contribute exactly zero
    np.add.at(c, cols.ravel(), vals.ravel().astype(c.dtype, copy=False))
    return c


def pc_checksum(pc, mat) -> np.ndarray | None:
    """``c_M = Mᵀ·1`` for preconditioner kinds whose operator form is
    known host-side at setup; None when unavailable (the M-channel ABFT
    check is then skipped and pc.apply corruption is left to the drift
    gate / sentinels)."""
    n = mat.shape[0]
    kind = getattr(pc, "kind", None)
    if kind == "none":
        return np.ones(n)
    if kind == "jacobi":
        # M = diag(1/d) is symmetric: c_M = M·1 = 1/d, from the same
        # host-side diagonal the PC setup itself uses
        pmat = getattr(pc, "_mat", None) or mat
        d = np.asarray(pmat.diagonal())
        with np.errstate(divide="ignore", invalid="ignore"):
            c = np.where(d != 0, 1.0 / d, 0.0)
        return c
    return None


def checksum_tolerance_dtype(dtype) -> float:
    """Machine epsilon of the REAL scalar of ``dtype`` — the unit the
    ``-ksp_abft_tol`` multiplier scales.

    Under a mixed-precision plan the guarded kernels pass the STORAGE
    dtype here even though the checksum partials accumulate in the f32
    reduce channel: the benign error of a low-precision apply is set by
    the storage rounding (bf16: eps ~7.8e-3), and a threshold scaled to
    the accumulation epsilon would flag every healthy bf16 apply.
    ``utils.dtypes.real_eps`` handles the ml_dtypes family np.finfo
    rejects."""
    from ..utils.dtypes import real_eps
    return real_eps(dtype)
