"""Resilient solves: fault injection, retry/backoff, checkpoint-resume,
and graceful solver fallback (README "Resilience").

Submodules:

* :mod:`.faults` — deterministic fault-injection harness (named fault
  points at the solve/communication boundaries; ``TPU_SOLVE_FAULTS`` env
  spec or :func:`inject_faults` context manager);
* :mod:`.retry` — :class:`RetryPolicy` + :func:`resilient_solve`
  (checkpoint → backoff → rebuild → resume on retriable device failures);
* :mod:`.fallback` — :class:`KSPFallbackChain` (method escalation on
  breakdown/NaN, reduced-precision retry on device OOM);
* :mod:`.abft` — ABFT column checksums + trace-time silent-corruption
  applicator (README "Silent-error detection");
* :mod:`.elastic` — degraded-mesh recovery from PERSISTENT device loss
  (:class:`ElasticPolicy` + :class:`MeshRebuilder`; the ``mesh_shrink``
  escalation stage retry.py engages once the
  :class:`~.faults.HealthMonitor` classifies repeated failures as a
  loss — README "Elastic recovery").

``faults`` is stdlib-only and imported eagerly (``parallel/mesh.py``
depends on it); ``retry``/``fallback``/``elastic`` import solver
machinery and load lazily to keep this package importable from anywhere
in the framework.
"""

from . import faults
from . import abft
from .faults import FaultSpecError, HealthMonitor, inject_faults

__all__ = [
    "faults", "abft", "inject_faults", "FaultSpecError", "HealthMonitor",
    "RetryPolicy", "resilient_solve", "resilient_solve_many",
    "default_checkpoint_path",
    "KSPFallbackChain", "reduced_dtype",
    "ElasticPolicy", "MeshRebuilder",
]


def __getattr__(name):
    if name in ("RetryPolicy", "resilient_solve", "resilient_solve_many",
                "default_checkpoint_path"):
        from . import retry
        return getattr(retry, name)
    if name in ("KSPFallbackChain", "reduced_dtype"):
        from . import fallback
        return getattr(fallback, name)
    if name in ("ElasticPolicy", "MeshRebuilder"):
        from . import elastic
        return getattr(elastic, name)
    raise AttributeError(name)
