"""tpu-sparse-solve: TPU-native distributed sparse linear algebra.

A brand-new framework with the capability surface of the petsc4py/slepc4py
MPI example (`Dxslab/mpi-petsc4py-example`): distributed AIJ-style sparse
matrices and vectors, Krylov solvers with preconditioners, a Hermitian
eigensolver, a PETSc-style options database and row-block data distribution —
re-designed for TPU (JAX/XLA/Pallas): row-sharded HBM storage over a
`jax.sharding.Mesh`, jit-compiled `shard_map` Krylov loops whose reductions
are `lax.psum` collectives over ICI, and `device_put`-based data placement
replacing MPI point-to-point scatter.

See SURVEY.md at the repo root for the reference analysis this builds to.
"""

import os as _os

# The reference stack is fp64-native (PETSc/MUMPS). JAX canonicalizes to
# float32 unless x64 is enabled, which would silently truncate the library's
# float64 defaults — so enable it at import, PETSc-style. Opt out with
# TPU_SOLVE_NO_X64=1 (e.g. for pure-fp32 TPU benchmarking).
if _os.environ.get("TPU_SOLVE_NO_X64", "0") != "1":
    import jax as _jax

    _jax.config.update("jax_enable_x64", True)

# Subprocess-friendly platform override: the axon TPU plugin's sitecustomize
# overrides the JAX_PLATFORMS env var, so honor our own knob via jax.config
# (needed by tools/tpurun and tests that spawn drivers on forced-CPU meshes).
_plat = _os.environ.get("TPU_SOLVE_PLATFORM")
if _plat:
    import jax as _jax

    _jax.config.update("jax_platforms", _plat)

# Persistent XLA compilation cache: fresh-process driver runs (tpurun, the
# reference test2.py flow) are compile-dominated (~5-6 s for the eigensolver
# factorization program vs a ~0.5 s solve); caching compiled executables on
# disk cuts repeat runs to the solve cost. On by default — point elsewhere
# with TPU_SOLVE_COMPILE_CACHE=<dir>, disable with TPU_SOLVE_COMPILE_CACHE=0.
_cache = _os.environ.get(
    "TPU_SOLVE_COMPILE_CACHE",
    _os.path.join(_os.path.expanduser("~"), ".cache",
                  "mpi_petsc4py_example_tpu", "jax"))
if _cache and _cache != "0":
    import jax as _jax

    try:
        _jax.config.update("jax_compilation_cache_dir", _cache)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except AttributeError:  # older jax without the knobs
        pass

from .parallel.mesh import (DeviceComm, get_default_comm, set_default_comm,
                            as_comm, init_multihost)
from .parallel.partition import (
    RowLayout, row_partition, ownership_range, slice_csr_block,
    partition_csr, concat_csr_blocks)
from .core.vec import Vec
from .core.mat import Mat
from .core.shell import ShellMat
from .core.nullspace import NullSpace
from .solvers.pc import PC
from .solvers.ksp import KSP
from .solvers.refine import RefinedKSP
from .utils.convergence import (BatchedSolveResult, ConvergedReason,
                                RecoveryEvent, SolveResult)
from .utils.errors import (DeadlineExceededError, DeviceExecutionError,
                           ServerOverloadedError, SilentCorruptionError)
from .utils.options import Options, global_options, init, backend
from .utils import petsc_io
from . import resilience
from . import telemetry
from .resilience.faults import inject_faults

__version__ = "0.1.0"

__all__ = [
    "DeviceComm", "get_default_comm", "set_default_comm", "as_comm",
    "init_multihost",
    "RowLayout", "row_partition", "ownership_range", "slice_csr_block",
    "partition_csr", "concat_csr_blocks",
    "Vec", "Mat", "ShellMat", "NullSpace", "PC", "KSP", "RefinedKSP",
    "EPS", "ST", "SVD",
    "ConvergedReason", "RecoveryEvent", "SolveResult",
    "BatchedSolveResult",
    "DeviceExecutionError", "SilentCorruptionError",
    "DeadlineExceededError", "ServerOverloadedError",
    "Options", "global_options", "init", "backend", "petsc_io",
    "resilience", "telemetry", "inject_faults", "RetryPolicy",
    "resilient_solve",
    "resilient_solve_many", "ElasticPolicy", "HealthMonitor",
    "KSPFallbackChain",
    "SolveServer", "ServedSolveResult", "ServerClosedError",
    "SolveRouter", "QoSClass", "AutoscalePolicy",
    "MultisplitSolver", "MultisplitResult", "StaleExchange",
]


def __getattr__(name):
    # EPS/ST/SVD + resilience solver wrappers imported lazily to keep base
    # import light
    if name == "EPS":
        from .solvers.eps import EPS
        return EPS
    if name == "ST":
        from .solvers.st import ST
        return ST
    if name == "SVD":
        from .solvers.svd import SVD
        return SVD
    if name in ("RetryPolicy", "resilient_solve",
                "resilient_solve_many", "KSPFallbackChain",
                "ElasticPolicy", "HealthMonitor"):
        return getattr(resilience, name)
    if name in ("SolveServer", "ServedSolveResult", "ServerClosedError",
                "SolveRouter", "QoSClass", "AutoscalePolicy"):
        # the serving layer pulls in KSP + resilience machinery — lazy,
        # like the other solver-object imports above
        from . import serving as _serving
        return getattr(_serving, name)
    if name in ("MultisplitSolver", "MultisplitResult"):
        from .solvers import multisplit as _multisplit
        return getattr(_multisplit, name)
    if name == "StaleExchange":
        from .parallel.exchange import StaleExchange
        return StaleExchange
    raise AttributeError(name)
