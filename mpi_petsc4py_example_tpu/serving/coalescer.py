"""Request coalescing for the solve server (serving/server.py).

The coalescer is deliberately PURE host logic — no threads, no device
work — so its grouping semantics are unit-testable in isolation and the
server's dispatcher thread stays the only place concurrency lives.

Semantics (the batching contract the server's tests pin):

* requests are compatible — and may share one ``KSP.solve_many`` block —
  only when they target the SAME registered operator with the SAME
  tolerances (rtol, atol, max_it): tolerances are runtime scalars of one
  compiled program launch, so a block has exactly one convergence
  contract. Mixed-tolerance requests NEVER batch together.
* FIFO order is preserved within a compatibility group, and groups are
  dispatched in order of their oldest member — a coalesced server must
  not reorder a client's causally ordered submissions to the same
  session.
* a group wider than ``max_k`` splits into ceil(k/max_k) blocks
  (the ``-ksp_batch_limit`` discipline applied at the serving layer,
  where the split can also respect arrival order).
* optionally a block's width is PADDED up to the next power of two
  (zero RHS columns — they converge at iteration 0 under the masked
  block-CG kernel and freeze): the set of compiled program widths is
  then bounded by log2(max_k) + 1 instead of one shape-specialized
  program per distinct request count, which is what keeps a long-lived
  server's compile count (and AOT blob population) finite.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any


@dataclass
class SolveRequest:
    """One pending solve: the unit the coalescer groups.

    ``future`` is the ``concurrent.futures.Future`` the client holds;
    the server resolves it with a per-request
    :class:`~.server.ServedSolveResult` after the batch it rode in
    returns. ``t_submit`` (``time.monotonic``) feeds the queue-wait
    statistics and the batching-window deadline.
    """
    op: str
    b: Any                      # (n,) host RHS, already dtype-validated
    rtol: float
    atol: float
    max_it: int
    future: Any
    # the PRECISION PLAN of the session the request targets (the storage
    # dtype string, e.g. "float32"/"bfloat16") — part of the
    # compatibility key: a block is ONE compiled program launch, and the
    # precision plan is compiled into it, so requests against operators
    # registered at different precisions must never share a block even
    # if a future server aliases several precision variants of one
    # operand set under related names.
    precision: str = ""
    # the REDUCTION-PLAN SCHEDULE of the session ("cg" / "pipecg" /
    # "sstep:<s>") — part of the compatibility key for the same reason
    # as precision: the schedule (and sstep's s, which sizes the traced
    # basis) is compiled into the block program, so requests solved
    # under different schedules must never share one. Today a session
    # name maps to exactly one KSP configuration, but a re-registered
    # session (the fleet-migration landing path) or a future
    # multi-schedule alias must not be able to batch across schedules.
    schedule: str = ""
    # QoS (serving/qos.py): the request's class label ("" = unlabeled)
    # and its priority tier (LOWER is more urgent; unlabeled requests
    # sit at qos.DEFAULT_PRIORITY between interactive and bulk). NOT
    # part of the compatibility key: a block launch costs the same
    # whoever rides it, so compatible mixed-priority requests may share
    # one — the scheduler orders BATCHES by their most urgent member
    # (qos.schedule), it never splits compatible work to enforce rank.
    qos: str = ""
    priority: int = 50
    # the request's telemetry span (telemetry.start_span("serving.request")
    # — DETACHED: opened on the submitting client thread, finished on the
    # dispatcher thread at resolution, linked to its batch's
    # serving.dispatch span by the batch_span attribute). None/no-op when
    # telemetry is disabled; NOT part of the compatibility key.
    span: Any = None
    t_submit: float = field(default_factory=time.monotonic)
    # absolute time.monotonic() the request must have DISPATCHED by, or
    # None for no deadline (serving/server.py resolves expired requests
    # with DeadlineExceededError instead of giving them a batch column).
    # NOT part of the compatibility key: deadlines shape admission, not
    # the convergence contract of the block a request rides in.
    t_deadline: float | None = None

    @property
    def key(self) -> tuple:
        """Compatibility key: requests batch together iff keys match
        (same operator, same tolerances, same precision plan, same
        reduction-plan schedule)."""
        return (self.op, str(self.precision), str(self.schedule),
                float(self.rtol), float(self.atol), int(self.max_it))

    def expired(self, now: float) -> bool:
        """Whether the request's dispatch deadline has passed."""
        return self.t_deadline is not None and now >= self.t_deadline


def coalesce(requests, max_k: int):
    """Group pending ``requests`` into dispatchable batches.

    Returns a list of request lists: one list per ``(compatibility key,
    max_k-chunk)``, FIFO within each batch, batches ordered by oldest
    member. Never mixes compatibility keys in one batch.
    """
    groups: dict = {}
    for r in requests:
        # dict insertion order IS the oldest-member group order
        groups.setdefault(r.key, []).append(r)
    max_k = max(1, int(max_k))
    batches = []
    for g in groups.values():
        for s in range(0, len(g), max_k):
            batches.append(g[s:s + max_k])
    return batches


def padded_width(k: int, max_k: int, pad_pow2: bool) -> int:
    """The dispatched block width for ``k`` coalesced requests: ``k``
    itself, or the next power of two (capped at ``max_k``) when padding
    is on — see the module docstring for why padding bounds the
    program-cache population."""
    if not pad_pow2 or k <= 0:
        return k
    p = 1 << max(k - 1, 0).bit_length()
    return min(max(p, 1), max(int(max_k), k))
