"""SolveServer — a persistent, device-resident solve session.

The bench data (BENCH_r05 / ROADMAP item 1) shows the on-chip CG loop at
~35k iters/s while an end-to-end solve spends ~95% of its wall in
per-request dispatch/launch latency. The serving answer is to stop
paying that latency per request: a long-lived :class:`SolveServer`
session registers each operator ONCE — CSR/ELL/DIA operands, PC
factors, and the AOT-cached compiled programs stay resident in device
HBM — and a concurrent stream of solve requests is COALESCED into
``(n, k)`` blocks dispatched through the PR-4 block-CG kernels
(``KSP.solve_many``: collective count per iteration independent of k),
with donated iterate buffers on the hot path (krylov ``donate=True``:
zero extra device allocations per launch). This is the PETSc
reuse-the-KSP-object idiom (PARITY.md "Serving sessions") made
concurrent: JAXMg and JAX-AMG (PAPERS.md) both keep solver state
device-resident between solves for exactly this reason.

Client APIs:

* :meth:`SolveServer.submit` — async: returns a
  ``concurrent.futures.Future`` resolving to a
  :class:`ServedSolveResult` (per-request iterations/residual/reason +
  the solution vector).
* :meth:`SolveServer.solve` — sync: submit + wait.

Requests are grouped by the coalescer (serving/coalescer.py): same
operator + same tolerances may share a block; a batching window
(``-solve_server_window``) holds the first request briefly so
concurrent arrivals ride the same launch; ``-solve_server_max_k`` caps
the block width and ``-solve_server_pad_pow2`` rounds widths up to
powers of two so a server compiles at most log2(max_k)+1 block
programs per operator configuration.

Resilience rides along PER REQUEST: with ``-solve_server_resilient``
(default on) every dispatched block runs under
:func:`resilience.retry.resilient_solve_many` — a worker crash
checkpoints the partial iterate block, backs off
(:meth:`RetryPolicy.serving`'s short deterministic delays), rebuilds,
and resumes; a detected silent corruption rolls the block back to the
verified iterates and re-enters immediately, and the PR-5 per-column
detection means one poisoned request cannot contaminate its
batch-mates' verified answers (the independent final re-verification
covers every column).

PERSISTENT device loss rides the elastic escalation
(resilience/elastic.py): when a dispatch's recovery trail reports a
``mesh_shrink`` — the resilient wrapper already resharded the failing
session and replayed its in-flight batch-mates from the checkpointed
iterate block — the server ADOPTS the degraded mesh: every other
resident operator is rebuilt on it and re-warmed at the block widths
traffic has used, so the session survives losing hardware instead of
dying with it. Degraded capacity also demands admission control, so
the server carries two hardening knobs: ``-solve_server_max_queue``
bounds the pending queue (excess submissions are REJECTED with a typed
:class:`~..utils.errors.ServerOverloadedError` instead of queueing
unboundedly) and ``-solve_server_deadline`` gives each request a
server-side dispatch deadline (expired requests resolve with
:class:`~..utils.errors.DeadlineExceededError` rather than occupying a
batch column). Every pending future always resolves — a result, a
typed rejection, or the dispatch error — never a hang.

The fleet round adds the QoS tier (serving/qos.py) on top: requests
carry priority + deadline CLASSES (``submit(qos="interactive")``), the
dispatcher runs a deadline-weighted scheduling pass per window and
dispatches ONE batch at a time — a p99-sensitive arrival preempts
queued bulk batches into the next pass, never an in-flight block — and
under overload the admission tier sheds the least-urgent pending bulk
request (typed resolution) before rejecting interactive arrivals. The
PR-8 shrink adoption also gained its inverse: when
:func:`resilience.faults.heal` restores devices, the dispatcher adopts
the largest viable LARGER mesh (``-elastic_regrow``), rebuilding every
resident session on it — lost capacity comes back without restarting
the server. Multi-replica deployments front N of these servers with
:class:`~.fleet.SolveRouter`.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

import numpy as np

from ..core.mat import Mat
from ..parallel.mesh import as_comm
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy, resilient_solve_many
from ..solvers.ksp import KSP
from ..telemetry import flight as _flight
from ..telemetry import metrics as _metrics
from ..telemetry import spans as _telemetry
from ..utils.convergence import SolveResult
from ..utils.errors import DeadlineExceededError, ServerOverloadedError
from ..utils.options import global_options
from ..utils.profiling import (record_admission, record_qos,
                               record_serving)
from . import qos as _qos
from .coalescer import SolveRequest, padded_width


class ServerClosedError(RuntimeError):
    """Submission to a server that has been shut down."""


@dataclass
class ServedSolveResult(SolveResult):
    """A per-request :class:`SolveResult` as demultiplexed from the
    coalesced block it rode in.

    ``x`` is the request's solution vector (host copy of its block
    column); ``batch_width`` the number of REAL requests coalesced into
    the dispatched block (padding columns excluded); ``queue_wait`` the
    seconds the request waited between submission and dispatch (the
    batching-window + backlog cost the latency percentiles in
    benchmarks/run_all.py cfg9 report). ``wall_time`` is the whole
    block's wall — launches are shared, so per-request wall is not a
    meaningful quantity. The resilience trail (``attempts`` /
    ``recovery_events`` / SDC counters) is the BLOCK's: recovery acts on
    the dispatched block as a unit.
    """
    x: object = None
    op: str = ""
    batch_width: int = 1
    queue_wait: float = 0.0


class _OperatorSession:
    """One registered operator: device-resident operands + a dedicated
    KSP whose PC factors and compiled programs persist across requests.

    The registered tolerance DEFAULTS are stored here, not read back
    from the KSP: dispatches set the session KSP's tolerances to each
    batch's (possibly overridden) values, so the KSP object's own
    rtol/atol/max_it drift with traffic while these stay the contract
    ``register_operator`` documented."""

    __slots__ = ("name", "operator", "ksp", "dtype", "n",
                 "rtol", "atol", "max_it", "multisplit", "persistent")

    def __init__(self, name, operator, ksp, multisplit=None,
                 persistent=None):
        self.name = name
        self.operator = operator
        self.ksp = ksp
        self.dtype = np.dtype(operator.dtype)
        self.n = int(operator.shape[0])
        self.rtol = float(ksp.rtol)
        self.atol = float(ksp.atol)
        self.max_it = int(ksp.max_it)
        self.multisplit = multisplit   # async-tier solver, or None
        self.persistent = persistent   # PersistentRunner, or None

    @property
    def schedule(self) -> str:
        """The session's reduction-plan schedule ("cg" / "pipecg" /
        "sstep:<s>" / "multisplit") — part of every request's
        compatibility key (serving/coalescer.py): the schedule is
        compiled into the block program, so blocks never mix schedules.
        "multisplit" is the ASYNC schedule class: jittery-mesh sessions
        route to the stale-tolerant tier (solvers/multisplit.py) and
        never coalesce with synchronous-plan sessions."""
        if self.multisplit is not None:
            return "multisplit"
        tp = self.ksp.get_type()
        return f"{tp}:{int(self.ksp.sstep_s)}" if tp == "sstep" else tp


class SolveServer:
    """Long-lived solve session with request coalescing (module doc).

    Parameters (each overridable at construction time by the options DB
    — PETSc precedence: runtime flags beat programmatic defaults):

    window
        Batching window in seconds (``-solve_server_window``): the
        dispatcher holds the OLDEST pending request this long so
        concurrent arrivals coalesce into its block. 0 dispatches
        every snapshot of the queue immediately.
    max_k
        Maximum coalesced block width (``-solve_server_max_k``).
    pad_pow2
        Round block widths up to powers of two with zero columns
        (``-solve_server_pad_pow2``) — bounds the compiled-program
        population; a zero column freezes at iteration 0 under the
        masked block-CG kernel.
    resilient
        Dispatch through ``resilient_solve_many``
        (``-solve_server_resilient``).
    retry_policy
        The :class:`RetryPolicy` for resilient dispatches; default
        :meth:`RetryPolicy.serving` (short deterministic backoff —
        clients are waiting). ``-solve_server_retry_delay`` overrides
        its base delay.
    max_queue
        Admission control (``-solve_server_max_queue``): pending-queue
        bound above which :meth:`submit` raises
        :class:`ServerOverloadedError` instead of enqueueing. 0 (the
        default) queues unboundedly.
    deadline
        Default server-side dispatch deadline in seconds per request
        (``-solve_server_deadline``); a request still queued past it
        resolves with :class:`DeadlineExceededError`. 0 disables;
        :meth:`submit` takes a per-request override.
    autostart
        Start the dispatcher thread immediately. ``False`` lets tests
        (and batch drivers) enqueue a known request population and then
        :meth:`start` — every pending request is then coalesced in one
        deterministic window.
    """

    def __init__(self, comm=None, *, window: float = 0.002,
                 max_k: int = 32, pad_pow2: bool = True,
                 resilient: bool = True,
                 retry_policy: RetryPolicy | None = None,
                 max_queue: int = 0, deadline: float = 0.0,
                 autostart: bool = True):
        self.comm = as_comm(comm)
        # the mesh this server was PROVISIONED on: the re-grow ceiling
        # (shrink adoption moves self.comm down the ladder; a heal may
        # move it back up, never past this)
        self._full_comm = self.comm
        self._heal_epoch_seen = _faults.heal_epoch()
        self.window = float(window)
        self.max_k = int(max_k)
        self.pad_pow2 = bool(pad_pow2)
        self.resilient = bool(resilient)
        self.retry_policy = retry_policy or RetryPolicy.serving()
        self.max_queue = int(max_queue)
        self.deadline = float(deadline)
        self.qos_classes = _qos.builtin_classes()
        self._sessions: dict[str, _OperatorSession] = {}
        self._pending: list[SolveRequest] = []
        # batches left over from the last scheduling pass, valid while
        # _pending is untouched by submit/shed: draining an N-request
        # backlog then costs ONE schedule, not one per dispatched batch
        self._sched_cache: list | None = None
        self._inflight = 0
        self._stop = False
        self._closed = False
        self._cv = threading.Condition()
        # serializes SESSION MUTATION (regrow/adopt rebuilds, operator
        # un/registration) against in-flight dispatches: the dispatcher
        # holds it across _dispatch, so a public regrow()/unregister
        # from another thread waits for the current block instead of
        # swapping operators under it (RLock: the dispatcher's own
        # shrink-adoption path re-enters)
        self._session_lock = threading.RLock()
        self._thread: threading.Thread | None = None
        self._dispatch_hook = None       # test seam: called per batch
        self._stats = {"requests": 0, "batches": 0, "padded_cols": 0,
                       "width_hist": {}, "qos_hist": {},
                       "rejected": 0, "expired": 0, "shed": 0,
                       "mesh_shrinks": [], "mesh_regrows": []}
        # per-server queue-wait histogram: the SAME Histogram type (and
        # .summary percentile code path) the process-wide registry twin
        # uses — SolveServer.stats() and profiling.serving_stats() can
        # no longer drift in how they compute p50/p99
        self._wait_hist = _metrics.Histogram(
            "serving.queue_wait_seconds",
            _metrics.QUEUE_WAIT_BUCKETS_S)
        self.set_from_options()
        if autostart:
            self.start()

    # ---- configuration ------------------------------------------------------
    def set_from_options(self):
        """Apply ``-solve_server_*`` runtime flags (utils/options)."""
        opt = global_options()
        self.window = opt.get_real("solve_server_window", self.window)
        self.max_k = opt.get_int("solve_server_max_k", self.max_k)
        self.pad_pow2 = opt.get_bool("solve_server_pad_pow2",
                                     self.pad_pow2)
        self.resilient = opt.get_bool("solve_server_resilient",
                                      self.resilient)
        self.max_queue = opt.get_int("solve_server_max_queue",
                                     self.max_queue)
        self.deadline = opt.get_real("solve_server_deadline",
                                     self.deadline)
        delay = opt.get_real("solve_server_retry_delay", None)
        if delay is not None:
            # REPLACE, never mutate: the caller may share one
            # RetryPolicy object with non-serving resilient solves
            import dataclasses
            self.retry_policy = dataclasses.replace(
                self.retry_policy, base_delay=float(delay))
        return self

    setFromOptions = set_from_options

    # ---- operator registry --------------------------------------------------
    def register_operator(self, name: str, A, *, ksp_type: str = "cg",
                          pc_type: str = "jacobi", dtype=None,
                          rtol: float = 1e-5, atol: float = 0.0,
                          max_it: int = 10000, abft: bool = False,
                          residual_replacement: int = 0,
                          megasolve: bool = False,
                          multisplit: bool = False,
                          persistent: bool = False,
                          warm_widths=()):
        """Register operator ``name`` and make its solve state resident.

        ``A`` is a framework operator (Mat / matrix-free stencil) or
        anything ``Mat.from_scipy`` accepts (scipy sparse, dense
        ndarray). Registration builds the session KSP, places the
        operands, and sets up the PC ONCE — every later request reuses
        the resident factors and cached programs. ``rtol/atol/max_it``
        are the session DEFAULTS a request may override per submit
        (different tolerances then coalesce separately).

        ``warm_widths`` pre-compiles (and AOT-caches) the block
        programs for the given widths by dispatching zero-RHS blocks —
        they converge at iteration 0 — so the first real request at
        that width pays no compile.

        ``abft`` / ``residual_replacement`` arm the PR-5
        silent-corruption guard on the session: an in-program detection
        rolls the whole block back to the verified iterates and the
        resilient dispatch re-enters immediately — one poisoned request
        cannot contaminate its batch-mates (per-column detection +
        independent final re-verification). ``megasolve`` routes the
        session's coalesced dispatches through the FUSED whole-solve
        program (solvers/megasolve.py): a served block — refinement
        recurrence, true-residual verification and all — costs exactly
        ONE compiled-program launch, the measurement the
        ``serving.dispatch`` span's ``dispatches`` attribute reports.
        The session KSP also applies the options DB (``-ksp_*`` flags —
        abft, residual replacement, true-residual gating, megasolve —
        override these defaults at runtime, the PETSc precedence).

        ``persistent`` (or ``-solve_server_persistent``) registers the
        session in PERSISTENT serving mode (serving/persistent.py):
        dispatched batches stage into a double-buffered device-resident
        multi-request program — one ``persistent_serve`` launch drains
        up to ``max_k`` request slots, each a full megasolve with
        per-slot masked independence and per-slot tolerances — so
        sustained traffic pays amortized ≪ 1 program dispatch per
        request. Requires a megasolve-eligible configuration without
        the ABFT guard (ineligible sessions warn and fall back to
        per-batch dispatch); implies ``megasolve`` for the resilient
        fallback path.

        ``multisplit`` routes the session to the ASYNCHRONOUS tier
        (solvers/multisplit.py): requests dispatch per-column through
        the stale-tolerant outer iteration instead of a coalesced
        synchronous block — the schedule class for jittery or degrading
        meshes, where any synchronous plan pays max-of-device latency
        per reduction. QoS-``interactive`` batches ride FRESHER
        exchanges: their staleness bound tightens to
        ``-multisplit_urgent_stale`` (default: half the session bound).
        ``ksp_type``/``pc_type`` then configure the per-block INNER
        solves (with ``-multisplit_inner_*`` flags taking precedence).
        """
        if name in self._sessions:
            raise ValueError(f"operator {name!r} already registered")
        op = A
        if not hasattr(op, "device_arrays"):
            import scipy.sparse as sp
            op = Mat.from_scipy(self.comm, sp.csr_matrix(A), dtype=dtype)
        ksp = KSP().create(self.comm)
        ksp.set_operators(op)
        ksp.set_type(ksp_type)
        ksp.get_pc().set_type(pc_type)
        ksp.set_tolerances(rtol=rtol, atol=atol, max_it=max_it)
        ksp.abft = bool(abft)
        ksp.residual_replacement = int(residual_replacement)
        ksp.megasolve = bool(megasolve)
        ksp.set_from_options()
        # the options DB keeps PETSc precedence, but a global -ksp_type/
        # -pc_type aimed at some OTHER solver in the process can silently
        # turn this session's coalesced block dispatch into per-column
        # sequential solves (KSP.solve_many's fallback routing) — results
        # stay correct, the serving throughput win evaporates. Say so.
        from ..solvers.krylov import batched_pc_supported
        if (not multisplit
                and (ksp.get_type() not in ("cg", "pipecg", "sstep")
                     or not batched_pc_supported(ksp.get_pc()))):
            import warnings
            warnings.warn(
                f"SolveServer operator {name!r}: configuration "
                f"{ksp.get_type()}+{ksp.get_pc().get_type()} has no "
                "batched kernel — coalesced blocks will dispatch as "
                "per-column sequential solves (check for stray global "
                "-ksp_type/-pc_type options)", stacklevel=2)
        ksp.set_up()                  # PC factors placed NOW, once
        ms = None
        if multisplit:
            from ..solvers.multisplit import MultisplitSolver
            if not hasattr(op, "to_scipy"):
                raise ValueError(
                    f"operator {name!r}: the multisplit schedule class "
                    "needs a host-reconstructible operator (Mat) — "
                    "matrix-free stencils have no row splitting")
            # the session's ksp_type/pc_type seed the per-block inner
            # solves — unless -multisplit_inner_type is set (PETSc
            # precedence: runtime flags beat programmatic defaults)
            inner = (None if global_options().has("multisplit_inner_type")
                     else ksp.get_type())
            ms = MultisplitSolver(self.comm, inner_type=inner,
                                  pc_type=ksp.get_pc().get_type(),
                                  rtol=rtol, atol=atol, dtype=dtype)
            ms.set_operator(op)
        persistent = global_options().get_bool("solve_server_persistent",
                                               persistent)
        pr_wanted = bool(persistent) and ms is None
        if persistent and ms is not None:
            raise ValueError(
                f"operator {name!r}: persistent and multisplit are "
                "mutually exclusive schedule classes — the async tier "
                "has no coalesced block program to keep resident")
        if pr_wanted:
            from ..solvers.megasolve import megasolve_supported
            guard = bool(ksp.abft) or int(ksp.residual_replacement) > 0
            if guard or not megasolve_supported(ksp.get_type(),
                                                ksp.get_pc(), op, nrhs=2):
                import warnings
                warnings.warn(
                    f"SolveServer operator {name!r}: persistent serving "
                    "needs a megasolve-eligible configuration without "
                    "the ABFT guard — falling back to per-batch "
                    "dispatch", stacklevel=2)
                pr_wanted = False
            else:
                # the recovery path (serving/persistent.py fallback)
                # dispatches through the session KSP: keep it on the
                # fused per-batch program
                ksp.megasolve = True
        sess = _OperatorSession(name, op, ksp, multisplit=ms)
        if pr_wanted:
            from .persistent import PersistentRunner
            sess.persistent = PersistentRunner(self, sess)
        with self._session_lock:
            # under the session lock: a concurrent regrow/adoption must
            # not iterate the registry while it grows
            self._sessions[name] = sess
            for w in warm_widths:
                w = padded_width(int(w), self.max_k, self.pad_pow2)
                ksp.solve_many(np.zeros((sess.n, w), sess.dtype))
        return sess

    registerOperator = register_operator

    def register_session(self, name: str, operator, *,
                         ksp_type: str = "cg", pc_type: str = "jacobi",
                         **kw):
        """Register an operator that is ALREADY a framework Mat/stencil
        resident on (or rebuildable for) this server's mesh — the
        migration landing pad (serving/fleet.py): the router reloads the
        elastic checkpoint onto the destination comm and hands the
        re-placed operator here, so a migrated session never round-trips
        through scipy again. Same contract as
        :meth:`register_operator`."""
        return self.register_operator(name, operator, ksp_type=ksp_type,
                                      pc_type=pc_type, **kw)

    def unregister_operator(self, name: str):
        """Remove a resident session (the migration departure hook —
        serving/fleet.py). Refuses while requests for it are queued:
        callers drain first so no future can be orphaned; its device
        buffers are released with the session object."""
        with self._session_lock, self._cv:
            if any(r.op == name for r in self._pending):
                raise RuntimeError(
                    f"unregister_operator({name!r}): requests still "
                    "pending — drain() first")
            sess = self._sessions.pop(name, None)
        if sess is None:
            raise ValueError(f"unknown operator {name!r}; registered: "
                             f"{self.operators()}")
        return sess

    def operators(self):
        return sorted(self._sessions)

    # ---- client APIs --------------------------------------------------------
    def submit(self, op: str, b, *, rtol: float | None = None,
               atol: float | None = None, max_it: int | None = None,
               deadline: float | None = None, qos: str | None = None,
               priority: int | None = None) -> Future:
        """Enqueue one solve; returns a Future of ServedSolveResult.

        Tolerance overrides narrow the request's compatibility group —
        requests with different tolerances never share a block.
        ``deadline`` overrides the per-request dispatch deadline in
        seconds (0 = none; default: the named QoS class's deadline, else
        the server's). ``qos`` names a service class
        (``interactive``/``bulk`` — serving/qos.py): it sets the
        request's priority tier and default deadline; ``priority``
        overrides the tier directly (LOWER is more urgent). With the
        queue at ``max_queue``, an arrival first tries to SHED the
        least-urgent strictly-lower-priority pending request (its future
        resolves with the typed overload error — bulk sheds before
        interactive, nothing hangs); when nothing pending is less
        urgent, the arrival itself is rejected with
        :class:`ServerOverloadedError` (admission control — the caller
        sheds load).
        """
        sess = self._sessions.get(op)
        if sess is None:
            raise ValueError(f"unknown operator {op!r}; registered: "
                             f"{self.operators()}")
        b = np.asarray(b)
        if b.shape != (sess.n,):
            raise ValueError(f"submit({op!r}): b must be ({sess.n},), "
                             f"got {b.shape}")
        cls = _qos.resolve(qos, self.qos_classes)
        prio = (int(priority) if priority is not None
                else cls.priority if cls is not None
                else _qos.DEFAULT_PRIORITY)
        if deadline is not None:
            budget = float(deadline)
        elif cls is not None and cls.deadline > 0:
            budget = cls.deadline
        else:
            budget = self.deadline
        fut: Future = Future()
        req = SolveRequest(
            # a COPY of the caller's RHS: the request sits in the
            # batching window while the caller may reuse its buffer for
            # the next submission — a zero-copy view would silently
            # rewrite this request's RHS
            op=op, b=np.array(b, dtype=sess.dtype, copy=True),
            rtol=sess.rtol if rtol is None else float(rtol),
            atol=sess.atol if atol is None else float(atol),
            max_it=sess.max_it if max_it is None else int(max_it),
            # the session's storage dtype IS its precision plan — part
            # of the compatibility key (serving/coalescer.py), as is
            # the reduction-plan schedule (cg/pipecg/sstep:<s>)
            precision=str(sess.dtype),
            schedule=sess.schedule,
            qos=cls.name if cls is not None else "",
            priority=prio,
            future=fut)
        if budget > 0:
            req.t_deadline = req.t_submit + budget
        with self._cv:
            if self._closed:
                raise ServerClosedError("SolveServer is shut down")
            if self._sessions.get(op) is not sess:
                # the session was unregistered (a fleet migration's
                # departure) between validation above and this enqueue:
                # reject now rather than queue a request no dispatch
                # can serve
                raise ValueError(f"operator {op!r} was unregistered "
                                 "while submitting")
            if self.max_queue > 0 and len(self._pending) >= self.max_queue:
                victim = _qos.shed_victim(self._pending, prio)
                if victim is None:
                    self._stats["rejected"] += 1
                    record_admission(rejected=1)
                    raise ServerOverloadedError(len(self._pending),
                                                self.max_queue)
                # QoS shedding: the less-urgent victim gives its queue
                # slot to this arrival; its future RESOLVES with the
                # typed error (shed=True) — resolved, never dropped.
                # Removal by IDENTITY: dataclass equality would compare
                # the ndarray RHS payloads
                self._pending = [r for r in self._pending
                                 if r is not victim]
                self._stats["shed"] += 1
                record_admission(shed=1)
                if victim.future.set_running_or_notify_cancel():
                    victim.future.set_exception(ServerOverloadedError(
                        len(self._pending) + 1, self.max_queue,
                        shed=True))
                self._end_request_span(victim, "shed")
            record_qos(req.qos)
            # the request's span is opened only for ADMITTED requests
            # (rejections are counted by serving.rejected — a burst of
            # ~flight_len rejected submissions must not flush the
            # dispatch history out of the post-mortem ring), on the
            # client thread; it is finished at resolution on the
            # dispatcher thread and linked to the dispatch span it rode
            # in (no-op singleton when disabled)
            req.span = _telemetry.start_span("serving.request", op=op)
            self._pending.append(req)
            # the queue changed (appended here, possibly shed above):
            # the dispatcher must re-schedule — a new arrival may
            # preempt the cached batch order
            self._sched_cache = None
            _metrics.registry.gauge("serving.queue_depth").set(
                len(self._pending))
            self._cv.notify_all()
        return fut

    def solve(self, op: str, b, *, timeout: float | None = None,
              **tol_overrides) -> ServedSolveResult:
        """Synchronous client API: submit + wait."""
        return self.submit(op, b, **tol_overrides).result(timeout)

    # ---- lifecycle ----------------------------------------------------------
    def start(self):
        """Start the dispatcher thread (idempotent)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="SolveServer-dispatch",
                daemon=True)
            self._thread.start()
        return self

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every submitted request has resolved; False on
        timeout. The server stays open for new submissions."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while (self._pending or self._inflight
                   or self._persistent_unresolved()):
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return False
                self._cv.wait(rem if rem is not None else 0.5)
        return True

    def drain_operator(self, name: str,
                       timeout: float | None = None) -> bool:
        """Block until no request for ``name`` sits in the pending
        queue; False on timeout. Unlike :meth:`drain` this does NOT
        wait out traffic to co-resident sessions — the migration path
        (serving/fleet.py) uses it so moving one session off a busy
        replica cannot livelock behind the others' sustained load.
        An in-flight block for the session may still be executing;
        session swaps serialize on the session lock, which waits it
        out."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while any(r.op == name for r in self._pending):
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return False
                self._cv.wait(rem if rem is not None else 0.5)
        return True

    def shutdown(self, wait: bool = True):
        """Stop the server. ``wait=True`` (default) FLUSHES the queue —
        every pending future resolves (the drain-on-shutdown contract) —
        then joins the dispatcher. ``wait=False`` fails pending futures
        with :class:`ServerClosedError` and returns promptly."""
        with self._cv:
            if self._closed and self._thread is None:
                return
            self._closed = True
            if not wait:
                for r in self._pending:
                    if r.future.set_running_or_notify_cancel():
                        r.future.set_exception(
                            ServerClosedError("server shut down before "
                                              "dispatch"))
                    if r.span is not None:
                        r.span.set_attr("outcome", "closed").end()
                self._pending.clear()
                self._sched_cache = None
            pending = bool(self._pending)
        if self._thread is None and pending:
            # never-started server (autostart=False): flush inline so
            # shutdown keeps the every-future-resolves contract
            self.start()
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(wait=exc == (None, None, None))
        return False

    # ---- dispatcher ---------------------------------------------------------
    def _loop(self):
        while True:
            with self._cv:
                while (not self._pending and not self._stop
                       and not self._persistent_unresolved()):
                    self._cv.wait()
                stopping = not self._pending and self._stop
                idle = not self._pending
                t_open = (self._pending[0].t_submit if self._pending
                          else 0.0)
            if idle:
                # the queue went quiet (or we are stopping) with
                # persistent launches outstanding: resolve them NOW —
                # staged futures must never wait on the next arrival.
                # Outside _cv (resolution blocks on device results and
                # notifies the condvar) under the session lock, the
                # established lock order.
                with self._session_lock:
                    self._flush_persistent()
                if stopping:
                    return
                continue
            # a heal may have restored capacity while the server sat
            # degraded — adopt the larger mesh BEFORE dispatching this
            # window's traffic (cheap epoch check when nothing healed)
            self._maybe_regrow()
            # batching window: hold the oldest pending request at most
            # `window` seconds so concurrent arrivals ride its block;
            # shutdown flushes immediately. Requests arriving after the
            # scheduling pass below land in a LATER pass by construction
            # — and the window is only charged once per backlog: a
            # request requeued by the one-batch-per-pass discipline is
            # older than the window, so the next pass dispatches it
            # immediately.
            while True:
                with self._cv:
                    if self._stop:
                        break
                    rem = self.window - (time.monotonic() - t_open)
                    if rem <= 0:
                        break
                    self._cv.wait(timeout=rem)
            # QoS scheduling pass (serving/qos.py): group the snapshot
            # into compatible batches ordered by deadline-weighted
            # priority and dispatch ONE — the rest stay pending, so a
            # high-priority arrival during this batch's launch preempts
            # the remaining bulk batches into the next pass (never the
            # in-flight block: preemption is scheduling, not
            # cancellation). The remaining batch order is CACHED and
            # reused while nothing touches the queue (submit/shed
            # invalidate), so draining a quiet backlog schedules once,
            # not once per batch.
            with self._cv:
                if self._sched_cache:
                    batch = self._sched_cache.pop(0)
                else:
                    with _telemetry.span(
                            "serving.coalesce",
                            taken=len(self._pending)) as csp:
                        batches = _qos.schedule(self._pending,
                                                self.max_k)
                        csp.set_attrs(batches=len(batches))
                    if not batches:
                        continue
                    batch = batches[0]
                    self._sched_cache = batches[1:]
                chosen = {id(r) for r in batch}
                self._pending = [r for r in self._pending
                                 if id(r) not in chosen]
                self._inflight += len(batch)
                _metrics.registry.gauge("serving.queue_depth").set(
                    len(self._pending))
            try:
                with self._session_lock:
                    self._dispatch(batch)
            finally:
                with self._cv:
                    self._inflight -= len(batch)
                    self._cv.notify_all()

    def _dispatch(self, reqs):
        """Solve one coalesced batch and demux per-request results."""
        if self._dispatch_hook is not None:
            self._dispatch_hook(reqs)
        # server-side deadlines: a request whose dispatch deadline has
        # passed resolves with DEADLINE_EXCEEDED instead of occupying a
        # batch column — on a degraded (shrunk) mesh the capacity goes
        # to requests whose clients are still waiting
        now = time.monotonic()
        expired = [r for r in reqs if r.expired(now)]
        if expired:
            with self._cv:
                self._stats["expired"] += len(expired)
            record_admission(expired=len(expired))
            for r in expired:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(DeadlineExceededError(
                        now - r.t_submit, r.t_deadline - r.t_submit))
                self._end_request_span(r, "deadline_exceeded")
            reqs = [r for r in reqs if not r.expired(now)]
        # honor client-side cancellation (Future protocol): a request
        # cancelled before dispatch never reaches the device
        live = []
        for r in reqs:
            if r.future.set_running_or_notify_cancel():
                live.append(r)
            else:
                self._end_request_span(r, "cancelled")
        reqs = live
        if not reqs:
            return
        sess = self._sessions.get(reqs[0].op)
        if sess is None:
            # the session vanished after these requests were queued (an
            # out-of-contract unregister without a drain): resolve the
            # futures with the typed error — the dispatcher must NEVER
            # die on a bad batch, every later request depends on it
            exc = ValueError(f"operator {reqs[0].op!r} is no longer "
                             "registered")
            for r in reqs:
                r.future.set_exception(exc)
                self._end_request_span(r, "error")
            return
        k = len(reqs)
        t0 = time.monotonic()
        waits = [t0 - r.t_submit for r in reqs]
        with self._cv:
            qh = self._stats["qos_hist"]
            for r in reqs:
                key = r.qos or "default"
                qh[key] = qh.get(key, 0) + 1
        if sess.persistent is not None:
            # persistent serving: stage this batch's slots into the
            # resident program's NEXT launch (double-buffered;
            # serving/persistent.py) and return to coalescing
            # immediately — resolution happens at buffer turnover or
            # the idle flush, never here
            sess.persistent.enqueue(reqs, waits)
            self._record(k, waits, 0)
            return
        kpad = padded_width(k, self.max_k, self.pad_pow2)
        # the batch span: a ROOT span on the dispatcher thread; every
        # request resolved out of this block links back to it
        bsp = _telemetry.span("serving.dispatch", op=reqs[0].op,
                              width=k, padded=kpad - k,
                              precision=reqs[0].precision)
        with bsp:
            B = np.zeros((sess.n, kpad), dtype=sess.dtype)
            for j, r in enumerate(reqs):
                B[:, j] = r.b
            ksp = sess.ksp
            ksp.set_tolerances(rtol=reqs[0].rtol, atol=reqs[0].atol,
                               max_it=reqs[0].max_it)
            try:
                if sess.multisplit is not None:
                    res = self._multisplit_solve_many(sess, reqs, B, k)
                elif self.resilient:
                    res = resilient_solve_many(ksp, B,
                                               policy=self.retry_policy)
                else:
                    res = ksp.solve_many(B)
            # tpslint: disable=TPS005 — whatever the dispatch raised
            # (exhausted retries, validation, a non-retriable device
            # failure) must reach the WAITING CLIENT FUTURES, not kill
            # the dispatcher thread; re-raising here would hang every
            # later request
            except Exception as exc:  # noqa: BLE001
                bsp.set_attr("error", type(exc).__name__)
                # close the batch span FIRST (end() is idempotent; the
                # with-exit becomes a no-op) so the dump below includes
                # this failed dispatch's own span tree, then dump — the
                # failure just became the clients' problem (no-op
                # disarmed)
                bsp.end()
                _flight.auto_dump("serving dispatch failed: "
                                  f"{type(exc).__name__}")
                for r in reqs:
                    r.future.set_exception(exc)
                    self._end_request_span(r, "error", batch=bsp)
                self._record(k, waits, kpad - k)
                return
            shrinks = [e for e in res.recovery_events
                       if e.kind == "mesh_shrink"]
            if shrinks:
                # the resilient dispatch survived a persistent device
                # loss by resharding THIS session onto a degraded mesh
                # (its batch-mates replayed from the checkpointed block
                # inside the retry loop) — adopt the new mesh
                # server-wide
                self._adopt_shrunk_mesh(sess, shrinks,
                                        time.monotonic() - t0)
            per = res.per_rhs()
            for j, r in enumerate(reqs):
                col = per[j]
                out = ServedSolveResult(
                    iterations=col.iterations,
                    residual_norm=col.residual_norm,
                    reason=col.reason, wall_time=res.wall_time,
                    history=col.history,
                    attempts=res.attempts,
                    recovery_events=list(res.recovery_events),
                    abft_checks=res.abft_checks,
                    sdc_detections=res.sdc_detections,
                    residual_replacements=res.residual_replacements,
                    x=np.array(res.X[:, j]), op=r.op, batch_width=k,
                    queue_wait=waits[j])
                r.future.set_result(out)
                self._end_request_span(r, "ok", batch=bsp,
                                       iterations=col.iterations,
                                       queue_wait=waits[j])
            bsp.set_attrs(attempts=res.attempts,
                          iterations=max(res.iterations, default=0))
        self._record(k, waits, kpad - k)

    def _persistent_unresolved(self) -> int:
        """Requests staged into (or riding) persistent launches — the
        drain/shutdown and idle-flush accounting. Lock-free snapshot:
        a stale count only costs one extra condvar lap."""
        n = 0
        for s in list(self._sessions.values()):
            if s.persistent is not None:
                n += s.persistent.unresolved
        return n

    def _flush_persistent(self):
        """Resolve every outstanding persistent launch and drain the
        staged backlogs (serving/persistent.py). Caller holds the
        session lock (the runners' concurrency contract)."""
        for s in list(self._sessions.values()):
            if s.persistent is not None:
                s.persistent.flush()

    def _multisplit_solve_many(self, sess, reqs, B, k):
        """Dispatch one batch through the ASYNCHRONOUS tier: per-column
        stale-tolerant outer solves (solvers/multisplit.py) instead of a
        coalesced synchronous block program — the "multisplit" schedule
        class. QoS-URGENT batches ride fresher exchanges: when any
        member is ``interactive``, the staleness bound tightens to
        ``-multisplit_urgent_stale`` (default: half the session's
        bound), trading straggler tolerance for iterate freshness on
        the traffic that is actually waiting."""
        from ..utils.convergence import BatchedSolveResult
        ms = sess.multisplit
        bound = None
        if any(r.qos == "interactive" for r in reqs):
            bound = global_options().get_int(
                "multisplit_urgent_stale", max(1, ms.max_stale // 2))
        t0 = time.monotonic()
        X = np.zeros((sess.n, k), dtype=sess.dtype)
        iters, rnorms, reasons, hists = [], [], [], []
        for j, r in enumerate(reqs):
            res = ms.solve(B[:, j], rtol=r.rtol, atol=r.atol,
                           max_stale=bound)
            X[:, j] = res.x
            iters.append(int(res.iterations))
            rnorms.append(float(res.residual_norm))
            reasons.append(int(res.reason))
            hists.append([rn for _v, rn in res.history])
        return BatchedSolveResult(iterations=iters, residual_norms=rnorms,
                                  reasons=reasons,
                                  wall_time=time.monotonic() - t0, X=X,
                                  histories=hists)

    @staticmethod
    def _end_request_span(req, outcome: str, batch=None, **attrs):
        """Finish a request's detached serving.request span, linking it
        to the batch span it was resolved out of."""
        sp = req.span
        if sp is None:
            return
        if batch is not None and batch.span_id:
            sp.set_attr("batch_span", batch.span_id)
        sp.set_attrs(outcome=outcome, **attrs)
        sp.end()

    def _rebuild_sessions_on(self, comm_new, skip=None) -> dict:
        """Re-place every resident session on ``comm_new`` (operands,
        PC factors, ABFT checksums; base + previously seen block-width
        programs re-warmed/AOT-loaded) — the shared rebuild step of the
        shrink adoption AND the re-grow. ``skip`` excludes a session the
        elastic retry stage already rebuilt. Per-session failures are
        recorded, never raised: a session that cannot live on the new
        geometry must not abort adoption for the sessions that can —
        its next dispatch surfaces the recorded error on client
        futures. Runs on the dispatcher thread (the only place sessions
        are mutated mid-flight)."""
        from ..resilience import elastic as _elastic
        # persistent launches hold device buffers on the OLD mesh:
        # consume them first (quiesce resolves the in-flight launch,
        # leaving host-side staged slots to launch on the new geometry;
        # inside our own fallback's shrink adoption the record is
        # already detached — a no-op)
        for s in list(self._sessions.values()):
            if s.persistent is not None:
                s.persistent.quiesce()
        with self._cv:
            widths = sorted(padded_width(w, self.max_k, self.pad_pow2)
                            for w in self._stats["width_hist"])
        failures = {}
        for s in self._sessions.values():
            if s is skip:
                continue
            try:
                mat2 = _elastic.rebuild_operator(s.operator, comm_new)
                _elastic.rebuild_ksp(s.ksp, mat2)
                s.operator = mat2
                _elastic.warm(s.ksp, widths)
            # tpslint: disable=TPS005 — a session whose operator cannot
            # be rebuilt on the new mesh must not abort adoption for
            # the sessions that CAN: record it, keep going; its next
            # dispatch surfaces the recorded error on client futures
            except Exception as exc:  # noqa: BLE001
                failures[s.name] = repr(exc)
        return failures

    def _adopt_shrunk_mesh(self, shrunk_sess, shrink_events, dispatch_wall):
        """Adopt the degraded mesh a resilient dispatch landed on.

        ``shrunk_sess``'s KSP was already rebuilt by the elastic retry
        stage; every OTHER resident operator is re-registered via
        :meth:`_rebuild_sessions_on` so the next dispatch of any session
        runs on surviving hardware instead of failing on the lost
        device."""
        comm_new = shrunk_sess.ksp.comm
        if comm_new is self.comm or comm_new.size >= self.comm.size:
            return
        old_n = self.comm.size
        t0 = time.monotonic()
        shrunk_sess.operator = shrunk_sess.ksp.get_operators()[0]
        failures = self._rebuild_sessions_on(comm_new, skip=shrunk_sess)
        self.comm = comm_new
        # deliberately do NOT touch _heal_epoch_seen here: a heal that
        # landed WHILE this degraded dispatch was running must still
        # trigger _maybe_regrow on the next pass (resetting to the
        # current epoch would swallow it); a stale pre-degradation heal
        # costs one harmless grown_comm plan that the still-lost
        # registry rejects
        entry = {"old_devices": old_n, "new_devices": comm_new.size,
                 "dispatch_wall_s": float(dispatch_wall),
                 "adopt_wall_s": time.monotonic() - t0,
                 "resumed_iteration": max(
                     (e.iterations for e in shrink_events), default=0),
                 "rebuild_failures": failures}
        with self._cv:
            self._stats["mesh_shrinks"].append(entry)

    def _maybe_regrow(self) -> bool:
        """Cheap hot-loop check: when the server sits DEGRADED and
        :func:`resilience.faults.heal` ran since, plan and adopt the
        largest viable larger mesh (never past the provisioned one).
        Returns True when a re-grow was executed."""
        if self.comm.size >= self._full_comm.size:
            return False
        ep = _faults.heal_epoch()
        if ep == self._heal_epoch_seen:
            return False
        self._heal_epoch_seen = ep
        return self.regrow()

    def regrow(self) -> bool:
        """Rebuild every resident session onto the largest viable
        larger mesh over healed devices (the elastic ladder's upward
        direction — ``-elastic_regrow``); no-op (False) when the server
        is not degraded, the policy disarms re-growing, or the healed
        hardware does not support a strictly larger rung. The public
        twin of the dispatcher's heal-epoch check, for drivers that
        know a repair happened (a fleet router, an operator console) —
        safe from any thread: the session lock makes the rebuild wait
        out an in-flight dispatch instead of swapping operators under
        it."""
        from ..resilience import elastic as _elastic
        from ..utils.profiling import record_mesh_regrow
        policy = _elastic.ElasticPolicy.from_options()
        if not (policy.enabled and policy.regrow):
            return False
        with self._session_lock:
            grown = _elastic.MeshRebuilder(policy).grown_comm(
                self.comm, self._full_comm)
            if grown is None:
                return False
            old_n = self.comm.size
            t0 = time.monotonic()
            with _telemetry.span("serving.regrow", old_devices=old_n,
                                 new_devices=int(grown.size)) as gsp:
                failures = self._rebuild_sessions_on(grown)
                self.comm = grown
                wall = time.monotonic() - t0
                record_mesh_regrow(old_n, grown.size, wall)
                gsp.set_attrs(
                    rebuilt=len(self._sessions) - len(failures),
                    failures=len(failures))
        entry = {"old_devices": old_n, "new_devices": grown.size,
                 "adopt_wall_s": wall, "rebuild_failures": failures}
        with self._cv:
            self._stats["mesh_regrows"].append(entry)
        return True

    def _record(self, width, waits, padded):
        record_serving(width, waits, padded)   # the process-wide twin
        for w in waits:
            self._wait_hist.observe(float(w))
        with self._cv:
            st = self._stats
            st["requests"] += width
            st["batches"] += 1
            st["padded_cols"] += padded
            st["width_hist"][width] = st["width_hist"].get(width, 0) + 1

    # ---- observability ------------------------------------------------------
    def stats(self) -> dict:
        """Per-server coalescing statistics (profiling.serving_stats()
        keeps the process-wide twin printed by ``log_view``; both views
        compute their wait percentiles through the SAME registry
        ``Histogram.summary`` helper)."""
        with self._cv:
            st = self._stats
            out = {"requests": st["requests"], "batches": st["batches"],
                   "padded_cols": st["padded_cols"],
                   "width_hist": dict(st["width_hist"]),
                   "qos_hist": dict(st["qos_hist"]),
                   "rejected": st["rejected"], "expired": st["expired"],
                   "shed": st["shed"],
                   "pending": len(self._pending),
                   "devices": int(self.comm.size),
                   "mesh_shrinks": [dict(e)
                                    for e in st["mesh_shrinks"]],
                   "mesh_regrows": [dict(e)
                                    for e in st["mesh_regrows"]]}
            per = {s.name: dict(s.persistent.stats)
                   for s in self._sessions.values()
                   if s.persistent is not None}
            if per:
                out["persistent"] = per
        out["mean_width"] = (out["requests"] / out["batches"]
                             if out["batches"] else 0.0)
        s = self._wait_hist.summary((50, 99))
        if s["count"]:
            out["queue_wait_mean_s"] = s["mean"]
            out["queue_wait_p50_s"] = s["p50"]
            out["queue_wait_p99_s"] = s["p99"]
            out["queue_wait_max_s"] = s["max"]
        return out

    def metrics_endpoint(self) -> str:
        """The process-wide telemetry registry in Prometheus text
        exposition format (content type ``text/plain; version=0.0.4``)
        — mount it behind ``GET /metrics`` on whatever HTTP front-end
        fronts this server (the framework deliberately ships the
        PAYLOAD, not a web server)."""
        return _metrics.registry.prometheus_text()

    metricsEndpoint = metrics_endpoint

    def __repr__(self):
        return (f"SolveServer(ops={self.operators()}, "
                f"window={self.window:g}s, max_k={self.max_k}, "
                f"resilient={self.resilient})")
