"""Serving layer: persistent device-resident solve sessions + the fleet.

See :mod:`.server` (the SolveServer session + client APIs),
:mod:`.coalescer` (the pure request-grouping logic), :mod:`.qos`
(priority/deadline classes, the deadline-weighted scheduler, overload
shedding, the autoscale policy), and :mod:`.fleet` (the SolveRouter:
consistent-hash session sharding across replicas, migration, heal-driven
re-grow). README "Serving" / "Fleet serving" document the user surface;
PARITY.md "Serving sessions" maps the session model onto PETSc's
reuse-the-KSP-object idiom.
"""

from .coalescer import SolveRequest, coalesce, padded_width
from .fleet import HashRing, SolveRouter
from .persistent import PersistentRunner
from .qos import AutoscalePolicy, QoSClass, ScaleDecision
from .server import (ServedSolveResult, ServerClosedError, SolveServer)

__all__ = [
    "SolveServer", "ServedSolveResult", "ServerClosedError",
    "SolveRequest", "coalesce", "padded_width",
    "PersistentRunner",
    "SolveRouter", "HashRing",
    "QoSClass", "AutoscalePolicy", "ScaleDecision",
]
