"""Serving layer: persistent device-resident solve sessions + the fleet.

See :mod:`.server` (the SolveServer session + client APIs),
:mod:`.coalescer` (the pure request-grouping logic), :mod:`.qos`
(priority/deadline classes, the deadline-weighted scheduler, overload
shedding, the autoscale policy), :mod:`.fleet` (the SolveRouter:
consistent-hash session sharding across replicas, migration, heal-driven
re-grow), :mod:`.transport` (the deadline/retry/idempotency RPC layer)
and :mod:`.remote` (remote replicas, the lease failure detector, and
partition-tolerant failover — the multi-host fleet). README "Serving" /
"Fleet serving" / "Multi-host transport" document the user surface;
PARITY.md "Serving sessions" maps the session model onto PETSc's
reuse-the-KSP-object idiom.
"""

from .coalescer import SolveRequest, coalesce, padded_width
from .fleet import HashRing, SolveRouter
from .persistent import PersistentRunner
from .qos import AutoscalePolicy, QoSClass, ScaleDecision
from .remote import (FailoverEvent, FleetManager, RemoteReplica,
                     ReplicaHost)
from .server import (ServedSolveResult, ServerClosedError, SolveServer)
from .transport import (LoopbackTransport, Message, RpcClient,
                        RpcDeadlineError, RpcHost, SocketHostServer,
                        SocketTransport, TransportError,
                        TransportUnreachableError)

__all__ = [
    "SolveServer", "ServedSolveResult", "ServerClosedError",
    "SolveRequest", "coalesce", "padded_width",
    "PersistentRunner",
    "SolveRouter", "HashRing",
    "QoSClass", "AutoscalePolicy", "ScaleDecision",
    "Message", "RpcHost", "RpcClient",
    "LoopbackTransport", "SocketTransport", "SocketHostServer",
    "TransportError", "TransportUnreachableError", "RpcDeadlineError",
    "ReplicaHost", "RemoteReplica", "FleetManager", "FailoverEvent",
]
