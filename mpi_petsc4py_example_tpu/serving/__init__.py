"""Serving layer: persistent device-resident solve sessions.

See :mod:`.server` (the SolveServer session + client APIs) and
:mod:`.coalescer` (the pure request-grouping logic). README "Serving"
documents the user surface; PARITY.md "Serving sessions" maps the
session model onto PETSc's reuse-the-KSP-object idiom.
"""

from .coalescer import SolveRequest, coalesce, padded_width
from .server import (ServedSolveResult, ServerClosedError, SolveServer)

__all__ = [
    "SolveServer", "ServedSolveResult", "ServerClosedError",
    "SolveRequest", "coalesce", "padded_width",
]
