"""Remote replicas: the fleet router spanning hosts over the RPC layer.

Three pieces turn the process-local fleet (serving/fleet.py) into a
multi-host one WITHOUT changing the router's placement/migration logic:

* :class:`ReplicaHost` — the host-process side: one
  :class:`~.server.SolveServer` behind an :class:`~.transport.RpcHost`
  handler table. Besides the obvious verbs (register/solve/drain/stats)
  it keeps a per-session **elastic checkpoint** — refreshed after every
  resolved solve with the session's CUMULATIVE iteration count — and
  piggybacks ``{op: iteration}`` on every lease ping, so the client side
  always knows which checkpoints advanced and pulls only those.
* :class:`RemoteReplica` — the client stub implementing the replica
  interface ``SolveRouter`` already speaks (``register_operator`` /
  ``submit`` / ``drain`` / ``stats`` / ``shutdown`` / ``.comm``), so a
  router built with a stub factory shards sessions across hosts
  unchanged; migration ships the mesh-portable checkpoint bytes over the
  wire (the format never encoded a mesh size — PR 6's elastic property
  is what makes cross-geometry failover possible at all). A submit whose
  RPC fails past its deadline consults the ``failover`` hook and replays
  the SAME idempotency key on the session's new home — the in-flight
  future fails over instead of hanging.
* :class:`FleetManager` — hosts + stubs + router + the **lease-based
  failure detector**: ``lease_step()`` pings every host; a host missing
  ``-fleet_transport_suspect_after`` consecutive renewals is SUSPECTED
  (degraded routing: its stub shrinks per-call deadlines so in-flight
  work fails over quickly), ``-fleet_transport_confirm_after`` misses
  CONFIRMS the loss and re-homes its sessions onto survivors from their
  last pulled checkpoint — resumed past iteration 0, never from scratch
  (the ``fleet.failover`` span records ``resumed_iteration`` as the
  proof). Placement changes carry monotonic **epochs**; after a
  partition heals, :meth:`FleetManager.reconcile` gathers every live
  host's resident table and keeps exactly one registration per session
  (the router's authoritative owner when alive, else the highest epoch),
  unregistering orphans — a healed partition reconciles routing instead
  of split-braining.

The deliberate asymmetry with MPI (PARITY round 19): the reference gets
exactly-once and membership from the communicator world — and pays for
it by dying whole when a rank does. This tier earns the same guarantees
per-message (idempotency keys, leases, epochs) so the fleet outlives any
single host, the ULFM revoke/shrink story at serving granularity.
"""

from __future__ import annotations

import itertools
import os
import tempfile
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from ..telemetry import metrics as _metrics
from ..telemetry import spans as _telemetry
from ..utils.options import global_options
from .fleet import SolveRouter
from .server import ServedSolveResult, SolveServer
from .transport import (LoopbackTransport, RpcClient, RpcHost,
                        SocketHostServer, SocketTransport, TransportError)

__all__ = ["ReplicaHost", "RemoteReplica", "RemoteSession",
           "FleetManager", "FailoverEvent"]


def _ckpt_to_bytes(mat, X, B, iteration: int = 0) -> bytes:
    """The elastic checkpoint as wire bytes (the npz format is already
    mesh-portable; this only lifts it off the filesystem)."""
    from ..utils.checkpoint import save_solve_state_many
    fd, path = tempfile.mkstemp(suffix=".npz", prefix="tpu_fleet_ckpt_")
    os.close(fd)
    try:
        save_solve_state_many(path, mat, X, B, iteration=int(iteration))
        with open(path, "rb") as f:
            return f.read()
    finally:
        try:
            os.remove(path)
        except OSError:
            pass


def _ckpt_from_bytes(blob: bytes, comm):
    """(mat, X, B, iteration) reloaded onto ``comm``'s mesh."""
    from ..utils.checkpoint import load_solve_state_many
    fd, path = tempfile.mkstemp(suffix=".npz", prefix="tpu_fleet_ckpt_")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        return load_solve_state_many(path, comm)
    finally:
        try:
            os.remove(path)
        except OSError:
            pass


class ReplicaHost:
    """Host-process side of one remote replica (module doc).

    ``server`` may be supplied (socket drills reuse one built
    elsewhere); otherwise one is constructed from ``comm`` +
    ``server_kw``. The handler table lives behind the transport's
    idempotency cache, so every verb here may be delivered twice and
    must only OBSERVE that via the cache — none of them are re-run."""

    def __init__(self, server: SolveServer | None = None, *, comm=None,
                 host_index: int = 0, **server_kw):
        self.server = (server if server is not None
                       else SolveServer(comm, **server_kw))
        self.host_index = int(host_index)
        self._lock = threading.RLock()
        # op -> {"bytes", "iteration", "epoch", "kwargs"}: the freshest
        # elastic checkpoint of every resident session, refreshed after
        # each resolved solve with the CUMULATIVE iteration count — what
        # a confirmed-loss failover on some OTHER host resumes from
        self._ckpt: dict[str, dict] = {}
        self.rpc = RpcHost({
            "hello": self._h_hello,
            "ping": self._h_ping,
            "register": self._h_register,
            "unregister": self._h_unregister,
            "solve": self._h_solve,
            "drain": self._h_drain,
            "drain_operator": self._h_drain_operator,
            "stats": self._h_stats,
            "operators": self._h_operators,
            "resident": self._h_resident,
            "checkpoint": self._h_checkpoint,
            "regrow": self._h_regrow,
            "shutdown": self._h_shutdown,
        }, host_index=host_index)

    # ---- handlers (payload dict -> picklable reply) -------------------------

    def _h_hello(self, p):
        return {"host": self.host_index,
                "mesh": self.server.comm.fingerprint()}

    def _h_ping(self, p):
        with self._lock:
            its = {op: e["iteration"] for op, e in self._ckpt.items()}
        return {"host": self.host_index, "iterations": its}

    def _h_register(self, p):
        """Land a session from checkpoint bytes. ``resume=True`` with a
        checkpoint past iteration 0 warm-restarts the carried iterate
        block (``set_initial_guess_nonzero`` — the failover path's
        "never iteration 0" contract); the reply's ``resumed_iteration``
        is the checkpointed count the solve continued from."""
        op = p["op"]
        kwargs = dict(p.get("kwargs") or {})
        epoch = int(p.get("epoch", 0))
        mat, X, B, it = _ckpt_from_bytes(p["ckpt"], self.server.comm)
        sess = self.server.register_session(op, mat, **kwargs)
        resumed = 0
        iteration = int(it)
        if p.get("resume") and it > 0:
            resumed = int(it)
            sess.ksp.set_initial_guess_nonzero(True)
            try:
                res = sess.ksp.solve_many(np.asarray(B), np.asarray(X))
            finally:
                sess.ksp.set_initial_guess_nonzero(False)
            iteration = int(it) + int(max(res.iterations or [0]))
            X = np.asarray(res.X)
        with self._lock:
            self._ckpt[op] = {
                "bytes": _ckpt_to_bytes(sess.operator, np.asarray(X),
                                        np.asarray(B), iteration),
                "iteration": iteration, "epoch": epoch, "kwargs": kwargs}
        return {"host": self.host_index, "epoch": epoch,
                "resumed_iteration": resumed, "iteration": iteration,
                "mesh": self.server.comm.fingerprint()}

    def _h_unregister(self, p):
        op = p["op"]
        self.server.drain_operator(op)
        self.server.unregister_operator(op)
        with self._lock:
            self._ckpt.pop(op, None)
        return True

    def _h_solve(self, p):
        op = p["op"]
        b = np.asarray(p["b"])
        kw = dict(p.get("kw") or {})
        budget = float(p.get("timeout") or 120.0)
        res = self.server.submit(op, b, **kw).result(timeout=budget)
        self._refresh_ckpt(op, b, res)
        return {"op": op, "x": np.asarray(res.x),
                "iterations": int(res.iterations),
                "residual_norm": float(res.residual_norm),
                "reason": int(res.reason),
                "wall_time": float(res.wall_time),
                "batch_width": int(res.batch_width),
                "queue_wait": float(res.queue_wait)}

    def _refresh_ckpt(self, op: str, b, res):
        """Advance ``op``'s checkpoint past the solve that just
        resolved: the iterate block becomes the solution, the session
        iteration count accumulates — so a later failover provably
        resumes PAST iteration 0."""
        with self._lock:
            entry = self._ckpt.get(op)
            if entry is None:
                return
            sess = self.server._sessions.get(op)
            if sess is None:
                return
            n = int(sess.n)
            X = np.asarray(res.x, dtype=sess.dtype).reshape(n, -1)
            B = np.asarray(b, dtype=sess.dtype).reshape(n, -1)
            entry["iteration"] = (int(entry["iteration"])
                                  + int(res.iterations))
            entry["bytes"] = _ckpt_to_bytes(sess.operator, X, B,
                                            entry["iteration"])

    def _h_drain(self, p):
        return bool(self.server.drain(p.get("timeout")))

    def _h_drain_operator(self, p):
        self.server.drain_operator(p["op"])
        return True

    def _h_stats(self, p):
        return self.server.stats()

    def _h_operators(self, p):
        return self.server.operators()

    def _h_resident(self, p):
        with self._lock:
            return {op: int(e["epoch"]) for op, e in self._ckpt.items()}

    def _h_checkpoint(self, p):
        with self._lock:
            e = self._ckpt[p["op"]]
            return {"bytes": e["bytes"], "iteration": int(e["iteration"]),
                    "epoch": int(e["epoch"]),
                    "kwargs": dict(e["kwargs"])}

    def _h_regrow(self, p):
        return bool(self.server.regrow())

    def _h_shutdown(self, p):
        self.server.shutdown(wait=bool(p.get("wait", True)))
        return True


class RemoteSession:
    """What :meth:`RemoteReplica.register_operator` returns: the
    client-side placed operator (the router retains ``.operator`` for
    migration checkpoints) plus the host's registration reply."""

    __slots__ = ("name", "operator", "info")

    def __init__(self, name, operator, info=None):
        self.name = name
        self.operator = operator
        self.info = dict(info or {})


class RemoteReplica:
    """Client stub speaking the replica interface over one RpcClient.

    ``comm`` is the CLIENT-side device comm checkpoints are placed on
    when the router reloads one for migration (``.comm`` property — the
    stub's mesh stand-in; the host may run a different geometry, which
    the elastic format absorbs). ``failover`` is an optional
    ``callable(op, replica_name) -> RemoteReplica | None`` consulted
    when a solve RPC dies past its deadline: the SAME idempotency key
    replays on the returned stub, so the in-flight future fails over —
    exactly once — instead of hanging. ``epoch_source`` supplies the
    monotonic placement epochs (the FleetManager's counter; standalone
    stubs default to a private one)."""

    def __init__(self, client: RpcClient, *, name: str = "remote",
                 comm=None, failover=None, epoch_source=None,
                 solve_timeout: float = 120.0, max_workers: int = 4):
        self.client = client
        self.name = str(name)
        self._comm = comm
        self.failover = failover
        self.degraded = False       # set by the failure detector
        self.solve_timeout = float(solve_timeout)
        self._counter = itertools.count(1)
        self._epoch = epoch_source or (lambda c=itertools.count(1):
                                       next(c))
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(max_workers)),
            thread_name_prefix=f"rpc-{name}")
        self._ops: dict[str, dict] = {}

    @property
    def comm(self):
        return self._comm

    def _deadline(self) -> float:
        """Per-call budget: a SUSPECTED host gets a quarter of the
        normal deadline — degraded routing means in-flight work fails
        over fast instead of burning the full budget on a host the
        lease detector already distrusts."""
        d = self.client.deadline
        return d * 0.25 if self.degraded else d

    def hello(self) -> dict:
        return self.client.call("hello", {}, deadline=self._deadline())

    # ---- replica interface (what SolveRouter calls) -------------------------

    def register_operator(self, name: str, A, **kw):
        mat = A
        if not hasattr(mat, "device_arrays"):
            import scipy.sparse as sp
            from ..core.mat import Mat
            mat = Mat.from_scipy(self._comm, sp.csr_matrix(A),
                                 dtype=kw.get("dtype"))
        return self.register_session(name, mat, **kw)

    def register_session(self, name: str, operator, **kw):
        n = int(operator.shape[0])
        z = np.zeros((n, 1), dtype=np.dtype(operator.dtype))
        epoch = int(self._epoch())
        info = self.client.call(
            "register",
            {"op": name, "ckpt": _ckpt_to_bytes(operator, z, z, 0),
             "kwargs": dict(kw), "epoch": epoch, "resume": False},
            deadline=self.client.deadline,
            idem_key=f"{self.name}.register.{name}.{epoch}")
        self._ops[name] = dict(kw)
        return RemoteSession(name, operator, info)

    def unregister_operator(self, name: str):
        self.client.call("unregister", {"op": name},
                         deadline=self._deadline())
        self._ops.pop(name, None)

    def submit(self, op: str, b, **kw) -> Future:
        """One solve as a Future. The RPC is synchronous per call, so a
        small pool carries it off-thread; the idempotency key is fixed
        per LOGICAL submit — retries and failover replays reuse it, and
        the host-side cache makes the solve run exactly once no matter
        which host finally answers."""
        fut: Future = Future()
        idem = f"{self.name}.solve.{op}.{next(self._counter)}"
        payload = {"op": op, "b": np.asarray(b), "kw": dict(kw),
                   "timeout": self.solve_timeout}
        self._pool.submit(self._solve_task, op, payload, idem, fut)
        return fut

    def _solve_task(self, op, payload, idem, fut: Future):
        if not fut.set_running_or_notify_cancel():
            return
        try:
            try:
                reply = self.client.call("solve", payload,
                                         deadline=self._deadline(),
                                         idem_key=idem)
            except TransportError:
                target = (self.failover(op, self.name)
                          if self.failover is not None else None)
                if target is None:
                    raise
                # replay the SAME key on the session's new home: if the
                # dead host actually ran the solve, nobody can ask it —
                # the survivor executes from the re-homed checkpoint and
                # its own cache dedupes OUR retries from here on
                reply = target.client.call(
                    "solve", payload, deadline=target.client.deadline,
                    idem_key=idem)
            fut.set_result(_result_from_reply(reply))
        # tpslint: disable=TPS005 — the future boundary: every failure
        # (transport, typed serving error, handler crash) RESOLVES the
        # future; swallowing would mean a hung client
        except Exception as exc:  # noqa: BLE001
            fut.set_exception(exc)

    def solve(self, op: str, b, *, timeout: float | None = None, **kw):
        return self.submit(op, b, **kw).result(
            timeout if timeout is not None else self.solve_timeout)

    def operators(self):
        return self.client.call("operators", {},
                                deadline=self._deadline())

    def drain(self, timeout: float | None = None) -> bool:
        budget = (timeout if timeout is not None
                  else self.solve_timeout) + self.client.deadline
        return bool(self.client.call("drain", {"timeout": timeout},
                                     deadline=budget))

    def drain_operator(self, name: str):
        return self.client.call(
            "drain_operator", {"op": name},
            deadline=self.solve_timeout + self.client.deadline)

    def stats(self) -> dict:
        """The host server's stats dict — or an explicit `unreachable`
        skeleton when the host is gone, so fleet-wide aggregation keeps
        working across a loss (the router sums these keys)."""
        try:
            return self.client.call("stats", {},
                                    deadline=self._deadline())
        except TransportError:
            return {"requests": 0, "batches": 0, "padded_cols": 0,
                    "width_hist": {}, "qos_hist": {}, "rejected": 0,
                    "expired": 0, "shed": 0, "pending": 0, "devices": 0,
                    "mesh_shrinks": [], "mesh_regrows": [],
                    "mean_width": 0.0, "unreachable": True}

    def regrow(self) -> bool:
        try:
            return bool(self.client.call("regrow", {},
                                         deadline=self._deadline()))
        except TransportError:
            return False

    def shutdown(self, wait: bool = True):
        try:
            self.client.call("shutdown", {"wait": bool(wait)},
                             deadline=self._deadline())
        except TransportError:
            pass        # a dead host is, definitionally, shut down
        self._pool.shutdown(wait=False)

    def __repr__(self):
        return (f"RemoteReplica({self.name!r}, "
                f"host={self.client.host_index}, "
                f"degraded={self.degraded})")


def _result_from_reply(reply: dict) -> ServedSolveResult:
    return ServedSolveResult(
        iterations=int(reply["iterations"]),
        residual_norm=float(reply["residual_norm"]),
        reason=int(reply["reason"]),
        wall_time=float(reply["wall_time"]),
        x=np.asarray(reply["x"]),
        op=str(reply["op"]),
        batch_width=int(reply["batch_width"]),
        queue_wait=float(reply["queue_wait"]))


@dataclass(frozen=True)
class FailoverEvent:
    """One confirmed host loss re-homed: which sessions moved where,
    and the checkpointed iteration the resumed solve continued from —
    ``resumed_iteration > 0`` is the drill's provable "never from
    scratch" evidence."""
    host: str
    dst: str
    sessions: tuple
    resumed_iteration: int
    wall_s: float


class FleetManager:
    """Hosts + transports + stubs + router + the failure detector.

    ``transport`` (or ``-fleet_transport``) picks ``loopback``
    (in-process, deterministic — CI and chaos drills) or ``socket``
    (localhost TCP — every frame really pickles and crosses a socket).
    Lease knobs come from the options DB: ``-fleet_transport_lease_s``
    between renewal rounds (only the monitor thread uses it —
    :meth:`lease_step` is manual and deterministic for drills),
    ``-fleet_transport_suspect_after`` / ``_confirm_after`` the
    consecutive-miss thresholds for the suspected/confirmed ladder.

    ``client_sleep`` is handed to every RpcClient (drills pass a no-op
    so retries don't wall-wait); ``monitor=True`` starts a daemon
    thread running the lease loop for real deployments."""

    def __init__(self, hosts: int = 2, comm=None, *,
                 transport: str | None = None, monitor: bool = False,
                 client_sleep=time.sleep, vnodes: int | None = None,
                 rpc_deadline: float | None = None,
                 rpc_retry_max: int | None = None, **server_kw):
        opt = global_options()
        self.transport_kind = opt.get_string(
            "fleet_transport", transport or "loopback")
        self.lease_s = opt.get_real("fleet_transport_lease_s", 0.5)
        self.suspect_after = opt.get_int("fleet_transport_suspect_after",
                                         2)
        self.confirm_after = opt.get_int("fleet_transport_confirm_after",
                                         4)
        self._epochs = itertools.count(1)
        self._lock = threading.RLock()
        self.hosts: dict[str, ReplicaHost] = {}
        self.stubs: dict[str, RemoteReplica] = {}
        self.transports: dict[str, object] = {}
        self._socket_servers: list[SocketHostServer] = []
        stubs = []
        for i in range(max(1, int(hosts))):
            name = f"r{i}"
            host = ReplicaHost(comm=comm, host_index=i, **server_kw)
            if self.transport_kind == "socket":
                srv = SocketHostServer(host.rpc)
                self._socket_servers.append(srv)
                tr = SocketTransport(srv.address, i)
            else:
                tr = LoopbackTransport(host.rpc)
            client = RpcClient(tr, deadline=rpc_deadline,
                               retry_max=rpc_retry_max, seed=i,
                               sleep=client_sleep)
            stub = RemoteReplica(client, name=name,
                                 comm=host.server.comm,
                                 failover=self.failover_target,
                                 epoch_source=self._next_epoch)
            self.hosts[name] = host
            self.stubs[name] = stub
            self.transports[name] = tr
            stubs.append(stub)
        pool = list(stubs)
        # the router names replicas r0, r1, ... in factory-call order —
        # popping in order keeps stub names and router names aligned
        self.router = SolveRouter(len(stubs), comm,
                                  vnodes=vnodes,
                                  server_factory=lambda: pool.pop(0))
        self._lease = {name: {"misses": 0, "status": "live"}
                       for name in self.stubs}
        # op -> {"bytes","iteration","kwargs","epoch","host"}: the
        # client-side checkpoint cache failover re-homes from — seeded
        # at registration, refreshed by lease_step whenever a ping shows
        # a session's iteration advanced
        self._ckpt: dict[str, dict] = {}
        self.failovers: list[FailoverEvent] = []
        self._closed = False
        self._monitor = None
        if monitor:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="fleet-lease",
                daemon=True)
            self._monitor.start()

    def _next_epoch(self) -> int:
        with self._lock:
            return next(self._epochs)

    # ---- session front-end --------------------------------------------------

    def register_operator(self, name: str, A, **kw):
        """Router registration + an immediate checkpoint pull, so the
        failover cache covers the session from birth (a host lost
        before the first lease round is still re-homeable)."""
        sess = self.router.register_operator(name, A, **kw)
        owner = self.router.owner(name)
        self._pull_ckpt(name, owner)
        return sess

    def submit(self, op: str, b, **kw) -> Future:
        return self.router.submit(op, b, **kw)

    def solve(self, op: str, b, *, timeout: float | None = None, **kw):
        return self.router.solve(op, b, timeout=timeout, **kw)

    def _pull_ckpt(self, op: str, owner: str):
        stub = self.stubs[owner]
        try:
            ck = stub.client.call("checkpoint", {"op": op},
                                  deadline=stub.client.deadline)
        except TransportError:
            return
        with self._lock:
            self._ckpt[op] = {"bytes": ck["bytes"],
                              "iteration": int(ck["iteration"]),
                              "kwargs": dict(ck["kwargs"]),
                              "epoch": int(ck["epoch"]), "host": owner}

    # ---- lease/heartbeat failure detector -----------------------------------

    def lease_step(self) -> dict:
        """One renewal round over every non-dead host: a reachable host
        resets its miss counter and reports per-session iterations (the
        checkpoint-freshness piggyback — advanced sessions get their
        checkpoint bytes pulled); an unreachable one climbs the
        suspected -> confirmed ladder. Deterministic and synchronous —
        drills call it directly; the monitor thread just loops it."""
        with self._lock:
            live = 0
            for name, stub in self.stubs.items():
                st = self._lease[name]
                if st["status"] == "dead":
                    continue
                try:
                    reply = stub.client.call(
                        "ping", {}, deadline=max(self.lease_s, 0.05))
                except TransportError:
                    st["misses"] += 1
                    _metrics.registry.counter("fleet.lease_misses").inc(
                        label=name)
                    if st["misses"] >= self.confirm_after:
                        self._confirm_loss(name)
                    elif st["misses"] >= self.suspect_after:
                        st["status"] = "suspected"
                        stub.degraded = True
                    continue
                st["misses"] = 0
                st["status"] = "live"
                stub.degraded = False
                live += 1
                for op, it in reply["iterations"].items():
                    cached = self._ckpt.get(op)
                    if (cached is None or cached["host"] != name
                            or int(it) > int(cached["iteration"])):
                        self._pull_ckpt(op, name)
            _metrics.registry.gauge("fleet.live_hosts").set(live)
            return {name: dict(st)
                    for name, st in self._lease.items()}

    def _monitor_loop(self):
        while not self._closed:
            try:
                self.lease_step()
            # tpslint: disable=TPS005 — the background lease loop must
            # outlive any single bad round (a host racing shutdown);
            # every per-host failure is already counted as a lease miss
            except Exception:  # noqa: BLE001
                pass
            time.sleep(self.lease_s)

    def _survivor(self, dead: str) -> str | None:
        """The re-home destination: a live host, else a merely
        suspected one (better a distrusted host than no host)."""
        with self._lock:
            for want in ("live", "suspected"):
                for name, st in self._lease.items():
                    if name != dead and st["status"] == want:
                        return name
        return None

    def _confirm_loss(self, name: str):
        """CONFIRMED host loss: kill its transport (no zombie replies),
        re-home every session it owned onto a survivor from the cached
        checkpoint — resumed at its checkpointed iteration, never 0 —
        and flip the router's placement (``rehome``). Idempotent: a
        second confirmation finds status already dead and returns."""
        with self._lock:
            st = self._lease[name]
            if st["status"] == "dead":
                return
            st["status"] = "dead"
            self.stubs[name].degraded = True
            tr = self.transports[name]
            if hasattr(tr, "kill"):
                tr.kill()
            t0 = time.perf_counter()
            owned = [op for op in self.router.operators()
                     if self.router.owner(op) == name]
            dst = self._survivor(name)
            moved = []
            resumed_max = 0
            with _telemetry.span("fleet.failover", host=name) as sp:
                if dst is not None:
                    for op in owned:
                        ck = self._ckpt.get(op)
                        if ck is None:
                            continue    # never seen a checkpoint: the
                            # session is lost with its host — reported
                            # below by its absence from `sessions`
                        stub = self.stubs[dst]
                        epoch = self._next_epoch()
                        reply = stub.client.call(
                            "register",
                            {"op": op, "ckpt": ck["bytes"],
                             "kwargs": ck["kwargs"], "epoch": epoch,
                             "resume": True},
                            deadline=stub.client.deadline,
                            idem_key=f"failover.{op}.{epoch}")
                        self.router.rehome(op, dst)
                        self._ckpt[op].update(
                            host=dst, epoch=epoch,
                            iteration=int(reply["iteration"]))
                        moved.append(op)
                        resumed_max = max(
                            resumed_max,
                            int(reply["resumed_iteration"]))
                sp.set_attrs(sessions=len(moved),
                             resumed_iteration=resumed_max)
            _metrics.registry.counter("fleet.failovers").inc(label=name)
            self.failovers.append(FailoverEvent(
                host=name, dst=dst or "", sessions=tuple(moved),
                resumed_iteration=resumed_max,
                wall_s=time.perf_counter() - t0))

    def failover_target(self, op: str, src_name: str):
        """The RemoteReplica failover hook: an in-flight solve RPC to
        ``src_name`` died past its deadline. Treat that as confirmation
        evidence (the retry budget IS a probe burst), re-home
        synchronously if nobody has yet, and return the stub now
        serving ``op`` — or None when no survivor exists (the caller's
        transport error then surfaces, typed, to the future)."""
        with self._lock:
            owner = self.router.owner(op)
            if (owner != src_name
                    and self._lease[owner]["status"] != "dead"):
                return self.stubs[owner]    # already re-homed
            self._confirm_loss(src_name)
            owner = self.router.owner(op)
            if (owner == src_name
                    or self._lease[owner]["status"] == "dead"):
                return None
            return self.stubs[owner]

    # ---- partition healing --------------------------------------------------

    def reconcile(self) -> dict:
        """Post-partition placement reconciliation (module doc): gather
        ``resident()`` from every reachable host; for each session keep
        exactly ONE registration — the router's authoritative owner
        when it is alive and actually resident, else the highest
        placement epoch — unregister the orphans, and point the router
        at the winner. Returns what moved, for drills to assert the
        single-truthful-placement property on."""
        with self._lock, _telemetry.span("fleet.reconcile") as sp:
            resident = {}
            for name, stub in self.stubs.items():
                if self._lease[name]["status"] == "dead":
                    continue
                try:
                    resident[name] = stub.client.call(
                        "resident", {}, deadline=stub.client.deadline)
                except TransportError:
                    continue        # still partitioned: next round
            orphans = []
            rehomed = []
            for op in self.router.operators():
                holders = {name: int(eps[op])
                           for name, eps in resident.items()
                           if op in eps}
                if not holders:
                    continue
                auth = self.router.owner(op)
                winner = (auth if auth in holders
                          else max(holders, key=holders.get))
                for name in sorted(holders):
                    if name == winner:
                        continue
                    self.stubs[name].client.call(
                        "unregister", {"op": op},
                        deadline=self.stubs[name].client.deadline)
                    orphans.append((op, name))
                if winner != auth:
                    self.router.rehome(op, winner)
                    self._pull_ckpt(op, winner)
                    rehomed.append((op, winner))
            sp.set_attrs(orphans=len(orphans), rehomed=len(rehomed))
            return {"orphans_removed": orphans, "rehomed": rehomed,
                    "resident": resident}

    # ---- drill/observability helpers ----------------------------------------

    def kill_host(self, name: str):
        """Abrupt host loss (drills): the transport dies NOW; discovery
        still flows through the lease ladder or an in-flight call's
        failover — exactly like a real host dropping off the network."""
        tr = self.transports[name]
        if hasattr(tr, "kill"):
            tr.kill()

    def lease_table(self) -> dict:
        with self._lock:
            return {name: dict(st) for name, st in self._lease.items()}

    def stats(self) -> dict:
        out = self.router.stats()
        out["lease"] = self.lease_table()
        out["failovers"] = [
            {"host": e.host, "dst": e.dst, "sessions": list(e.sessions),
             "resumed_iteration": e.resumed_iteration,
             "wall_s": e.wall_s}
            for e in self.failovers]
        return out

    def shutdown(self, wait: bool = True):
        self._closed = True
        self.router.shutdown(wait=wait)
        for srv in self._socket_servers:
            srv.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(wait=exc == (None, None, None))
        return False
