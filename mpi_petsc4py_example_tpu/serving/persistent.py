"""Persistent serving programs: the device-resident request queue.

Megasolve (PR 12) made a served block cost exactly ONE dispatch; this
module kills the remaining *per-batch* launch cost. A persistent
session owns a long-lived multi-request device program — the
``persistent_serve`` AOT kind (solvers/megasolve.py,
``build_megasolve_program_many(..., persistent=True)``): one dispatched
``lax.while_loop`` draining up to Q request SLOTS per launch, each slot
a full megasolve (fp64 refinement outer + nested CG-family inner plan,
verified-residual exit gate) with PER-SLOT masked independence — a
hard request refining in slot 3 never stalls the easy request that
froze in slot 0 at its own verified tolerance. Slots are independent
enough to carry *heterogeneous tolerances*: the program takes
``(Q,)``-shaped per-slot rtol/atol operands, so requests from
DIFFERENT coalescer compatibility groups ride one launch — the thing a
per-batch dispatch structurally cannot do.

The host side is a double buffer. While launch N executes on device,
the dispatcher keeps coalescing: every batch it routes here is STAGED
into launch N+1's operand slots (host-side; zero device traffic) and
the dispatcher returns to its queue immediately. Launch N+1 is
enqueued on the device stream *before* the host blocks fetching launch
N's results (JAX async dispatch), so the device never idles between
launches, and a burst of B batches costs ``ceil(B_requests / Q)``
launches — the amortized ≪ 1 dispatch/request the
``dispatch.programs`` counter proves under cfg17's sustained load.
Slot-count padding reuses the coalescer's pow2 discipline (a zero slot
carries zero tolerances: its residual norm is 0, its target 0, it
freezes at outer step 0). QoS ordering is preserved: slots fill in the
dispatcher's deadline-weighted batch order, FIFO.

Resolution points — every staged future resolves, never hangs:

- a staged backlog reaching Q slots turns the buffer over inline
  (resolve launch N, open launch N+1) — bounded memory AND latency
  under sustained load;
- the dispatcher's idle pass flushes every outstanding launch the
  moment the queue goes quiet (server._loop);
- ``drain``/``shutdown`` count staged + in-flight slots via
  ``SolveServer._persistent_unresolved`` and the dispatcher flushes
  before stopping.

Resilience: a fault inside the persistent loop must resolve EVERY
slot's future. When a fault plan is armed (or the mesh registry holds
a lost device), staging routes the whole launch through the per-batch
resilient path (``resilient_solve_many`` + the session's fused
megasolve) instead of the direct program call: the ``ksp.program``
boundary fires the fault, the retry tier rolls back to the per-slot
verified carries and re-enters past iteration 0, and an elastic shrink
is adopted server-wide — after which the next launch simply rebuilds
the persistent program on the surviving mesh (the program cache is
keyed on ``comm.mesh``; ``stats["rebuilds"]`` counts the reloads). A
launch that fails at resolve time takes the same fallback; a fallback
that itself fails resolves every slot future with the typed error —
exactly the dispatcher's never-hang contract.

PETSc has no analog: one ``KSPSolve`` per call is its serving model.
A resident multi-request program is a deliberate TPU-native divergence
(PARITY.md, round 18).
"""

from __future__ import annotations

import time

import numpy as np

from ..resilience import faults as _faults
from ..resilience.retry import resilient_solve_many
from ..telemetry import spans as _telemetry
from ..utils.convergence import ConvergedReason
from ..utils.profiling import record_requests_per_launch, record_sync
from .coalescer import padded_width

__all__ = ["PersistentRunner"]


class _Launch:
    """One in-flight persistent launch: the staged slot metadata plus
    the device output handles (or the fallback marker)."""

    __slots__ = ("reqs", "waits", "k", "kpad", "t0", "out", "fallback",
                 "span", "n")

    def __init__(self, reqs, waits, k, kpad, n):
        self.reqs = reqs
        self.waits = waits
        self.k = k
        self.kpad = kpad
        self.n = n
        self.t0 = time.monotonic()
        self.out = None          # device output tuple (direct path)
        self.fallback = False    # route through resilient_solve_many
        self.span = None


class PersistentRunner:
    """The per-session host half of persistent serving (module doc).

    All mutating entry points (``enqueue``/``flush``/``quiesce``) run
    under the server's ``_session_lock`` — the dispatcher thread for
    enqueue and the idle flush, any thread for the rebuild paths —
    so the staged list and the in-flight record need no lock of their
    own. The established lock order (``_session_lock`` before ``_cv``)
    is preserved: resolution notifies the server condvar LAST.
    """

    def __init__(self, server, sess, capacity: int | None = None):
        self._server = server
        self._sess = sess
        self.capacity = int(capacity or server.max_k)
        self._staged: list = []        # [(SolveRequest, wait_s), ...]
        self._rec: _Launch | None = None
        # live-request counter for drain/idle accounting: incremented
        # on enqueue, decremented only AFTER a slot's futures resolve —
        # deriving the count from _staged/_rec instead would read a
        # transient 0 while _launch holds slots in neither (program
        # build/compile), letting a concurrent drain exit early
        self._live = 0
        self._mesh = server.comm.mesh  # last launch's mesh (rebuild det.)
        # the ABFT-guard demotion warns ONCE per session registration —
        # under sustained traffic a per-dispatch warning is pure noise
        # (stats["fallbacks"] counts every demoted launch regardless)
        self._guard_warned = False
        self.stats = {"launches": 0, "requests": 0, "padded_slots": 0,
                      "fallbacks": 0, "rebuilds": 0, "turnovers": 0}

    # ---- dispatcher entry points -------------------------------------------
    def enqueue(self, reqs, waits):
        """Stage one coalesced batch's slots into the next launch.

        Returns immediately in the steady state — the launch is opened
        asynchronously when the buffer is free, and only a backlog at
        slot capacity forces an inline turnover (resolve the previous
        launch, open the next)."""
        self._live += len(reqs)
        self._staged.extend(zip(reqs, waits))
        if self._rec is None:
            self._launch()
            return
        # tpslint: disable=TPS015 — backlog turnover: each trip drains
        # a FULL launch (Q slots) and runs only while staged >= Q, so
        # dispatches stay at ceil(backlog/Q); the amortization this
        # rule asks for is what the loop body already does
        while self._rec is not None and len(self._staged) >= self.capacity:
            self.stats["turnovers"] += 1
            self._turn()

    def flush(self):
        """Resolve every outstanding launch and drain the staged
        backlog — the dispatcher's idle pass and the drain/shutdown
        path. Each turn opens the NEXT launch before blocking on the
        previous one (double buffer), so a deep backlog still overlaps
        host demux with device execution."""
        # tpslint: disable=TPS015 — this loop IS the amortizer: each
        # _turn dispatches one persistent_serve program that drains up
        # to Q staged requests, so trips scale with backlog/Q, not
        # with requests; there is no fused form above it to reach for
        while self._rec is not None or self._staged:
            self._turn()

    def quiesce(self):
        """Resolve the in-flight launch WITHOUT opening the next one —
        the mesh-rebuild hook (shrink adoption / re-grow): outstanding
        device buffers on the old mesh are consumed, while staged
        host-side slots stay staged and simply launch on the rebuilt
        mesh later. Reentrancy-safe: inside our own fallback's shrink
        adoption the in-flight record is already detached, so this is
        a no-op."""
        rec, self._rec = self._rec, None
        if rec is not None:
            self._resolve(rec)

    @property
    def unresolved(self) -> int:
        """Requests whose futures this runner still owes — staged,
        mid-launch, or riding the in-flight program. Read without the
        session lock: the counter only drops AFTER futures resolve, so
        a stale read errs on the side of one extra condvar lap, never
        an early drain exit."""
        return self._live

    # ---- launch / resolve ---------------------------------------------------
    def _turn(self):
        rec, self._rec = self._rec, None
        if self._staged:
            self._launch()           # enqueue N+1 before blocking on N
        if rec is not None:
            self._resolve(rec)

    def _launch(self):
        """Open a launch over the first ≤ capacity staged slots."""
        take = self._staged[: self.capacity]
        del self._staged[: len(take)]
        reqs = [r for r, _w in take]
        waits = [w for _r, w in take]
        k = len(reqs)
        kpad = padded_width(k, self.capacity, self._server.pad_pow2)
        sess = self._sess
        rec = _Launch(reqs, waits, k, kpad, sess.n)
        rec.span = _telemetry.start_span(
            "serving.persistent_launch", op=sess.name, width=k,
            padded=kpad - k)
        record_requests_per_launch(k)
        self.stats["launches"] += 1
        self.stats["requests"] += k
        self.stats["padded_slots"] += kpad - k
        # a fault plan armed (or a lost device still inside THIS
        # session's mesh) routes the launch through the resilient
        # per-batch path at resolve time: the ksp.program boundary must
        # FIRE the fault so the retry tier can roll back and re-enter —
        # the direct program call below would sail past host-level
        # fault points. A lost device the mesh already shrank around
        # does not force the fallback: the registry stays populated
        # until heal, but the surviving mesh is healthy.
        mesh_devs = set(sess.ksp.get_operators()[0].comm.device_ids)
        # a silent-corruption guard acquired AFTER registration
        # (ksp.abft / residual replacement toggled on the live session,
        # e.g. by a runtime -ksp_* flag) disqualifies the persistent
        # program — it carries no in-program detectors. Demote to the
        # resilient per-batch path, warning once per registration
        guard = (bool(sess.ksp.abft)
                 or int(sess.ksp.residual_replacement) > 0)
        if guard and not self._guard_warned:
            self._guard_warned = True
            import warnings
            warnings.warn(
                f"persistent session {sess.name!r}: the ABFT/"
                "residual-replacement guard was enabled after "
                "registration — launches fall back to per-batch "
                "dispatch (counted in stats['fallbacks']; this warns "
                "once per registration)", stacklevel=2)
        if (guard or _faults.active()
                or (set(_faults.lost_devices()) & mesh_devs)):
            rec.fallback = True
            self._rec = rec
            return
        try:
            rec.out = self._launch_device(rec)
        # tpslint: disable=TPS005 — a failed launch becomes the
        # fallback's problem (and ultimately the slot futures'), never
        # the dispatcher thread's
        except Exception:  # noqa: BLE001
            rec.fallback = True
        self._rec = rec

    def _launch_device(self, rec):
        """Stage operands and dispatch the persistent program — the
        per-slot-tolerance twin of KSP._solve_many_megasolve. Returns
        the device output handles WITHOUT blocking (JAX async
        dispatch): the host only blocks in _resolve."""
        import jax
        import jax.numpy as jnp

        from ..solvers.krylov import donation_supported
        from ..solvers.megasolve import (GATE_REFINE_MAX,
                                         build_megasolve_program_many,
                                         megasolve_stencil_supported)
        from ..utils.dtypes import tolerance_dtype
        sess = self._sess
        ksp = sess.ksp
        mat = ksp.get_operators()[0]
        pc = ksp.get_pc()
        comm = mat.comm
        if self._mesh is not None and self._mesh is not comm.mesh:
            # the session was rebuilt (shrink adoption / re-grow) since
            # the last launch: the program cache key carries comm.mesh,
            # so this launch transparently compiles/loads the
            # persistent program for the new geometry
            self.stats["rebuilds"] += 1
        self._mesh = comm.mesh
        op_dt = np.dtype(mat.dtype)
        sf = (ksp.megasolve_stencil_fastpath
              and megasolve_stencil_supported(ksp.get_type(), pc, mat,
                                              nrhs=rec.kpad))
        prog = build_megasolve_program_many(
            comm, ksp.get_type(), pc, mat, None, nrhs=rec.kpad,
            zero_guess=True, donate=True, sstep_s=ksp.sstep_s,
            stencil_fastpath=sf, persistent=True)
        B = np.zeros((sess.n, rec.kpad), dtype=op_dt)
        dt = tolerance_dtype(op_dt)
        rt = np.zeros(rec.kpad, dt)
        at = np.zeros(rec.kpad, dt)
        for j, r in enumerate(rec.reqs):
            B[:, j] = r.b
            rt[j] = r.rtol
            at[j] = r.atol
        # padding slots keep rtol = atol = 0 with a zero RHS: residual
        # norm 0, target 0 — frozen at outer step 0 by the mask
        maxit = max((r.max_it for r in rec.reqs), default=1)
        Bd, Xd0 = comm.put_rows_many([B, np.zeros_like(B)])
        if donation_supported():
            Xd0 = jnp.array(Xd0)      # op output, donation-safe
        _telemetry.record_program_dispatch("persistent_serve")
        return prog(mat.device_arrays(), pc.device_arrays(), Bd, Xd0,
                    rt, at, rt.copy(), dt.type(ksp.divtol),
                    np.int32(maxit), np.int32(GATE_REFINE_MAX),
                    np.int32(ConvergedReason.DIVERGED_MAX_IT))

    def _resolve(self, rec):
        """Block on a launch's device results and resolve every slot
        future; any failure demotes to the resilient fallback. Never
        raises — the dispatcher (and drain) depend on it."""
        try:
            if not rec.fallback:
                try:
                    self._resolve_device(rec)
                    return
                # tpslint: disable=TPS005 — a resolve-time failure
                # (device loss surfacing at fetch, donation misuse,
                # anything) must reach the slot futures through the
                # recovery path below, not kill the dispatcher
                except Exception:  # noqa: BLE001
                    rec.fallback = True
            self._resolve_fallback(rec)
        finally:
            # every slot future is resolved by now (result, recovered
            # result, or typed exception): release the drain count,
            # THEN wake the waiters
            self._live -= rec.k
            self._notify()

    def _resolve_device(self, rec):
        import jax

        from .server import ServedSolveResult, SolveServer
        Xd, steps, ii, rn, rs = rec.out[:5]
        fetch = jax.device_get((Xd, ii, rn, rs))
        record_sync("persistent launch resolve")
        wall = time.monotonic() - rec.t0
        X = np.asarray(fetch[0])[: rec.n]
        iters = np.asarray(fetch[1])
        rnorms = np.asarray(fetch[2])
        reasons = np.asarray(fetch[3]).astype(np.int64).copy()
        bad = ~np.isfinite(rnorms)
        reasons[bad] = ConvergedReason.DIVERGED_NANORINF
        for j, r in enumerate(rec.reqs):
            out = ServedSolveResult(
                iterations=int(iters[j]),
                residual_norm=float(rnorms[j]),
                reason=int(reasons[j]), wall_time=wall, history=[],
                x=np.array(X[:, j]), op=r.op, batch_width=rec.k,
                queue_wait=rec.waits[j])
            r.future.set_result(out)
            SolveServer._end_request_span(
                r, "ok", batch=rec.span, iterations=int(iters[j]),
                queue_wait=rec.waits[j])
        rec.span.set_attrs(outcome="ok", width=rec.k).end()

    def _resolve_fallback(self, rec):
        """The recovery path: one resilient per-batch megasolve over
        the launch's slots. Heterogeneous slot tolerances collapse to
        the strictest (min rtol/atol, max max_it) — every slot is
        solved at least as accurately as it asked. A persistent device
        loss shrinks the mesh through the elastic tier and the server
        adopts it; the NEXT launch rebuilds the persistent program on
        the surviving geometry."""
        from .server import ServedSolveResult, SolveServer
        self.stats["fallbacks"] += 1
        sess = self._sess
        ksp = sess.ksp
        reqs = rec.reqs
        t0 = time.monotonic()
        try:
            ksp.set_tolerances(
                rtol=min(r.rtol for r in reqs),
                atol=min(r.atol for r in reqs),
                max_it=max(r.max_it for r in reqs))
            B = np.zeros((sess.n, rec.kpad), dtype=sess.dtype)
            for j, r in enumerate(reqs):
                B[:, j] = r.b
            res = resilient_solve_many(
                ksp, B, policy=self._server.retry_policy)
        # tpslint: disable=TPS005 — exhausted retries / non-retriable
        # errors resolve every slot future typed; the dispatcher must
        # survive
        except Exception as exc:  # noqa: BLE001
            rec.span.set_attr("error", type(exc).__name__)
            rec.span.set_attrs(outcome="error").end()
            for r in reqs:
                r.future.set_exception(exc)
                SolveServer._end_request_span(r, "error", batch=rec.span)
            return
        shrinks = [e for e in res.recovery_events
                   if e.kind == "mesh_shrink"]
        if shrinks:
            self._server._adopt_shrunk_mesh(sess, shrinks,
                                            time.monotonic() - t0)
        per = res.per_rhs()
        for j, r in enumerate(reqs):
            col = per[j]
            out = ServedSolveResult(
                iterations=col.iterations,
                residual_norm=col.residual_norm,
                reason=col.reason, wall_time=res.wall_time,
                history=col.history, attempts=res.attempts,
                recovery_events=list(res.recovery_events),
                abft_checks=res.abft_checks,
                sdc_detections=res.sdc_detections,
                residual_replacements=res.residual_replacements,
                x=np.array(res.X[:, j]), op=r.op, batch_width=rec.k,
                queue_wait=rec.waits[j])
            r.future.set_result(out)
            SolveServer._end_request_span(
                r, "ok", batch=rec.span, iterations=col.iterations,
                queue_wait=rec.waits[j])
        rec.span.set_attrs(outcome="recovered",
                           attempts=res.attempts).end()

    def _notify(self):
        # lock order: we already hold _session_lock (all entry points
        # do); _cv nests inside it
        with self._server._cv:
            self._server._cv.notify_all()
