"""Message-based RPC transport for the multi-host fleet tier.

The fleet router (serving/fleet.py) was built against an in-process
replica interface; this module is the wire underneath it, so a replica
can live in another process (or, in CI, behind a deterministic loopback
that still exercises every failure mode). The design carries the three
guarantees ROADMAP item 2(a) needs from a transport, each enforced here
rather than hoped for at call sites:

1. **Every call has a deadline.** :meth:`RpcClient.call` takes a
   ``deadline`` budget (defaulting to ``-rpc_deadline_s``) and divides
   it across send attempts; no code path blocks forever. tpslint rule
   TPS019 pins the discipline repo-wide: a transport call site without a
   deadline/timeout argument does not lint.
2. **Retries are idempotent.** Each logical call carries an idempotency
   key; the host keeps a result cache plus an in-flight table, so a
   retried ``submit`` whose first delivery actually ran joins the
   original execution (or is served the cached outcome) — it can never
   double-solve, and the client-side future it feeds can never resolve
   twice. The MPI reference gets exactly-once by construction (a
   communicator either delivers or the job dies); an RPC fleet has to
   EARN it, and this cache is where.
3. **Failure is typed and injected, not emergent.** The fault registry
   (resilience/faults.py, TPS012) gained ``rpc.send`` / ``rpc.recv``
   points with drop / delay / duplicate / reorder / partition kinds;
   both transports consume them through :func:`faults.triggered`, so
   ``chaos_smoke --transport`` drills drive real message loss through
   the real code path. ``rpc.send`` fires on the CLIENT before the
   request leaves (device= selects the destination host index);
   ``rpc.recv`` fires on the host path AFTER the handler ran but BEFORE
   the reply leaves — the canonical duplicate-generating failure, since
   the client saw a timeout for work that actually happened.

Two transports share the client/host classes:

- :class:`LoopbackTransport` — in-process, deterministic, used by CI
  and the chaos drills. ``kill()`` models abrupt host loss: in-flight
  handler work completes host-side but no reply escapes.
- :class:`SocketTransport` / :class:`SocketHostServer` — localhost TCP
  with length-prefixed pickled frames, for real two-process drills and
  the cfg18 benchmark's socket rows.

Telemetry: each client call runs under an ``rpc.call`` span (method,
host, attempts), re-sends count into ``rpc.retries``, collapsed
duplicate deliveries into ``rpc.duplicates``, and total call wall
(including backoff) into the ``rpc.call_seconds`` histogram.
"""

from __future__ import annotations

import pickle
import random
import socket
import struct
import threading
import time
from dataclasses import dataclass, field

from ..resilience import faults as _faults
from ..telemetry import metrics as _metrics
from ..telemetry import spans as _telemetry
from ..utils.options import global_options

__all__ = [
    "Message",
    "TransportError",
    "TransportUnreachableError",
    "RpcDeadlineError",
    "RpcHost",
    "RpcClient",
    "LoopbackTransport",
    "SocketTransport",
    "SocketHostServer",
]


class TransportError(RuntimeError):
    """Base for transport-layer failures (never a handler failure —
    handler exceptions marshal through the reply and re-raise as their
    own types)."""


class TransportUnreachableError(TransportError):
    """One send attempt could not reach the host (or its reply was
    lost). Retriable: the client re-sends the SAME idempotency key."""


class RpcDeadlineError(TransportError):
    """The call's deadline budget expired across all retry attempts.

    The transport twin of the serving tier's queue-side
    ``DeadlineExceededError``: carries ``method``, ``host``,
    ``attempts`` and the ``deadline`` that ran out, so failover logic
    can distinguish "host gone" from "handler slow".
    """

    def __init__(self, method: str, host: int, attempts: int,
                 deadline: float):
        self.method = str(method)
        self.host = int(host)
        self.attempts = int(attempts)
        self.deadline = float(deadline)
        super().__init__(
            f"RPC DEADLINE_EXCEEDED: {method!r} to host {host} spent its "
            f"{deadline:.3f}s budget over {attempts} attempt(s) — the "
            "host is unreachable or the handler overran the deadline")


@dataclass
class Message:
    """One wire frame. ``idem`` is the idempotency key (stable across
    retries of the same logical call); ``seq`` the per-client send
    counter (distinct per attempt — how hosts could observe reordering);
    ``error`` carries the marshalled handler exception on replies."""
    kind: str                   # "request" | "reply"
    method: str
    seq: int = 0
    idem: str = ""
    payload: object = None
    error: object = None
    host: int = -1


def _marshal_exc(exc: Exception):
    """An exception object safe to ship in a reply: the original when it
    pickles (both transports may cross a process boundary), else a
    RuntimeError carrying its type name and message."""
    try:
        pickle.loads(pickle.dumps(exc))
        return exc
    # tpslint: disable=TPS005 — any pickling failure (recursion,
    # sockets, locks in exception state) degrades to the string form;
    # nothing is swallowed, the error still reaches the client
    except Exception:  # noqa: BLE001
        return RuntimeError(f"{type(exc).__name__}: {exc}")


class RpcHost:
    """Host-side dispatcher: named handlers behind an idempotency cache.

    ``handlers`` maps method name -> callable(payload) -> result. The
    cache has two tiers: ``_done`` (idem key -> ("ok", result) |
    ("err", exc)) and ``_inflight`` (idem key -> Event). A duplicate
    delivery whose original is still RUNNING waits on the event and
    returns the original's outcome (bounded by ``join_timeout`` — a
    duplicate must not hang past its caller's deadline either); a
    duplicate arriving after completion is served from ``_done``.
    Either way the handler body runs exactly once per key, which is the
    whole exactly-once story: the solve executes once, the future
    resolves once, no matter how many deliveries the network produced.

    The cache is bounded (``cache_cap``, FIFO eviction) so a
    long-running host does not grow it without limit; retries of one
    logical call arrive within its deadline, far inside any realistic
    cap.
    """

    def __init__(self, handlers: dict, host_index: int = 0, *,
                 cache_cap: int = 4096, join_timeout: float = 60.0):
        self.handlers = dict(handlers)
        self.host_index = int(host_index)
        self.cache_cap = int(cache_cap)
        self.join_timeout = float(join_timeout)
        self._done = {}
        self._order = []            # FIFO of done keys for eviction
        self._inflight = {}
        self._lock = threading.Lock()
        self.stats = {"calls": 0, "duplicates": 0, "errors": 0}

    def dispatch(self, msg: Message) -> Message:
        """Run (or join, or replay) the request; always returns a reply
        Message — handler exceptions marshal into ``reply.error``."""
        outcome = self._execute(msg)
        reply = Message(kind="reply", method=msg.method, seq=msg.seq,
                        idem=msg.idem, host=self.host_index)
        if outcome[0] == "ok":
            reply.payload = outcome[1]
        else:
            reply.error = outcome[1]
        return reply

    # ---- exactly-once core -------------------------------------------------

    def _execute(self, msg: Message):
        key = msg.idem
        if key:
            with self._lock:
                if key in self._done:
                    self.stats["duplicates"] += 1
                    _metrics.registry.counter("rpc.duplicates").inc(
                        label=msg.method)
                    return self._done[key]
                ev = self._inflight.get(key)
                if ev is None:
                    self._inflight[key] = threading.Event()
                else:
                    self.stats["duplicates"] += 1
            if ev is not None:
                _metrics.registry.counter("rpc.duplicates").inc(
                    label=msg.method)
                ev.wait(timeout=self.join_timeout)
                with self._lock:
                    done = self._done.get(key)
                if done is not None:
                    return done
                return ("err", TransportUnreachableError(
                    f"duplicate of {msg.method!r} joined an execution "
                    f"that did not finish within {self.join_timeout}s"))
        outcome = self._run(msg)
        if key:
            with self._lock:
                self._done[key] = outcome
                self._order.append(key)
                ev = self._inflight.pop(key, None)
                while len(self._order) > self.cache_cap:
                    self._done.pop(self._order.pop(0), None)
            if ev is not None:
                ev.set()
        return outcome

    def _run(self, msg: Message):
        self.stats["calls"] += 1
        handler = self.handlers.get(msg.method)
        if handler is None:
            self.stats["errors"] += 1
            return ("err", KeyError(
                f"no RPC handler for method {msg.method!r} on host "
                f"{self.host_index}"))
        try:
            return ("ok", handler(msg.payload))
        # tpslint: disable=TPS005 — the RPC boundary: every handler
        # exception is marshalled into the reply and re-raised client
        # side, the opposite of swallowing
        except Exception as e:  # noqa: BLE001
            self.stats["errors"] += 1
            return ("err", _marshal_exc(e))


# ---- transports ------------------------------------------------------------


def _apply_send_fault(host_index: int):
    """Consume an ``rpc.send`` clause for destination ``host_index``.
    Returns the number of deliveries (1 normally, 2 for ``duplicate``);
    raises :class:`TransportUnreachableError` for drop/partition (the
    client observes a timeout); sleeps ``mean=`` for delay/reorder (an
    overtaking delay IS reordering on a per-call transport)."""
    fault = _faults.triggered("rpc.send", device=host_index)
    if fault is None:
        return 1
    if fault.kind in ("drop", "partition"):
        raise TransportUnreachableError(
            f"rpc.send {fault.kind}: request to host {host_index} lost")
    if fault.kind in ("delay", "reorder"):
        time.sleep(max(0.0, float(fault.mean)))
        return 1
    if fault.kind == "duplicate":
        return 2
    return 1


def _apply_recv_fault(host_index: int):
    """Consume an ``rpc.recv`` clause on host ``host_index``'s reply
    path (the handler has ALREADY run). Returns "redeliver" for
    duplicate (the request is dispatched again — the idempotency cache's
    moment), raises for drop/partition (reply lost after real work),
    sleeps for delay/reorder."""
    fault = _faults.triggered("rpc.recv", device=host_index)
    if fault is None:
        return None
    if fault.kind in ("drop", "partition"):
        raise TransportUnreachableError(
            f"rpc.recv {fault.kind}: reply from host {host_index} lost "
            "after the handler ran")
    if fault.kind in ("delay", "reorder"):
        time.sleep(max(0.0, float(fault.mean)))
        return None
    if fault.kind == "duplicate":
        return "redeliver"
    return None


class LoopbackTransport:
    """In-process transport to one :class:`RpcHost` — deterministic CI
    stand-in for a network hop that still takes every failure the fault
    registry can inject, plus abrupt host death via :meth:`kill`.

    The dead flag is checked at call entry AND again before the reply is
    returned: killing a host mid-call means the handler's work happened
    (a solve really ran) but the client never hears — precisely the
    ambiguity failover logic must handle, reproduced on demand."""

    def __init__(self, host: RpcHost):
        self._host = host
        self.host_index = host.host_index
        self._dead = False

    def kill(self):
        """Abrupt host loss: every future call (and any reply not yet
        returned) fails with :class:`TransportUnreachableError`."""
        self._dead = True

    def revive(self):
        self._dead = False

    @property
    def dead(self) -> bool:
        return self._dead

    def call_once(self, msg: Message, timeout: float) -> Message:
        """One delivery attempt under ``timeout`` (loopback dispatch is
        synchronous, so the budget only bounds injected delays)."""
        if self._dead:
            raise TransportUnreachableError(
                f"host {self.host_index} is dead")
        deliveries = _apply_send_fault(self.host_index)
        reply = None
        for _ in range(deliveries):
            reply = self._host.dispatch(msg)
        if _apply_recv_fault(self.host_index) == "redeliver":
            reply = self._host.dispatch(msg)
        if self._dead:
            raise TransportUnreachableError(
                f"host {self.host_index} died before replying")
        return reply

    def close(self):
        self.kill()


def _send_frame(sock, obj, timeout: float):
    sock.settimeout(timeout)
    blob = pickle.dumps(obj)
    sock.sendall(struct.pack(">I", len(blob)) + blob)


def _recv_frame(sock, timeout: float):
    sock.settimeout(timeout)
    need = struct.unpack(">I", _recv_exact(sock, 4))[0]
    return pickle.loads(_recv_exact(sock, need))


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportUnreachableError("peer closed mid-frame")
        buf += chunk
    return buf


class SocketHostServer:
    """Host side of :class:`SocketTransport`: a localhost TCP listener
    feeding an :class:`RpcHost`, one thread per accepted connection
    (clients connect per call — the framing is 4-byte big-endian length
    + pickled :class:`Message`, one request/one reply per connection).
    """

    def __init__(self, host: RpcHost, *, port: int = 0,
                 frame_timeout: float = 30.0):
        self._host = host
        self.frame_timeout = float(frame_timeout)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", int(port)))
        self._sock.listen(32)
        self.address = self._sock.getsockname()
        self._closed = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="rpc-host-accept", daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return          # listener closed
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn):
        try:
            with conn:
                msg = _recv_frame(conn, self.frame_timeout)
                if self._closed:
                    return      # killed mid-call: work done, reply lost
                reply = self._host.dispatch(msg)
                if _apply_recv_fault(self._host.host_index) == "redeliver":
                    reply = self._host.dispatch(msg)
                if self._closed:
                    return
                _send_frame(conn, reply, self.frame_timeout)
        # tpslint: disable=TPS005 — a per-connection serving thread: any
        # framing/socket error just drops this connection (the client's
        # retry machinery is the recovery path, not this thread)
        except Exception:  # noqa: BLE001
            return

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    kill = close


class SocketTransport:
    """Client side of the localhost TCP transport: per-call connect to
    ``address`` with ``timeout``, one framed request, one framed reply.
    ``rpc.send`` faults apply client-side exactly like loopback (the
    recv-side faults live in :class:`SocketHostServer`)."""

    def __init__(self, address, host_index: int = 0):
        self.address = (str(address[0]), int(address[1]))
        self.host_index = int(host_index)
        self._dead = False

    def kill(self):
        self._dead = True

    @property
    def dead(self) -> bool:
        return self._dead

    def call_once(self, msg: Message, timeout: float) -> Message:
        if self._dead:
            raise TransportUnreachableError(
                f"host {self.host_index} is dead")
        deliveries = _apply_send_fault(self.host_index)
        reply = None
        budget = max(0.01, float(timeout))
        for _ in range(deliveries):
            try:
                with socket.create_connection(
                        self.address, timeout=budget) as sock:
                    _send_frame(sock, msg, budget)
                    reply = _recv_frame(sock, budget)
            except (OSError, EOFError, pickle.UnpicklingError) as e:
                raise TransportUnreachableError(
                    f"socket call to host {self.host_index} at "
                    f"{self.address} failed: {e}") from e
        return reply

    def close(self):
        self.kill()


# ---- client ----------------------------------------------------------------


@dataclass
class RetrySchedule:
    """Capped exponential backoff with deterministic jitter. ``base``
    doubles per attempt up to ``cap``; jitter draws uniformly from
    [0.5, 1.0]× the raw delay off a seeded PRNG so two clients that
    lost the same host do not re-send in lockstep, yet every drill
    replays identically."""
    base: float = 0.02
    cap: float = 0.5
    seed: int = 0
    _rng: random.Random = field(default=None, repr=False)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def delay(self, attempt: int) -> float:
        raw = min(self.cap, self.base * (2.0 ** max(0, attempt - 1)))
        return raw * (0.5 + 0.5 * self._rng.random())


class RpcClient:
    """Deadline-bounded, idempotent-retry client over one transport.

    Defaults come from the options database: ``-rpc_deadline_s`` (per
    call budget), ``-rpc_retry_max`` (send attempts per call),
    ``-rpc_backoff_base_s`` / ``-rpc_backoff_cap_s`` (the backoff
    curve). ``sleep`` is injectable so drills retry instantly.
    """

    def __init__(self, transport, *, deadline: float | None = None,
                 retry_max: int | None = None, seed: int = 0,
                 sleep=time.sleep):
        opt = global_options()
        self.transport = transport
        self.deadline = float(
            opt.get_real("rpc_deadline_s", 30.0)
            if deadline is None else deadline)
        self.retry_max = int(
            opt.get_int("rpc_retry_max", 4)
            if retry_max is None else retry_max)
        self.schedule = RetrySchedule(
            base=opt.get_real("rpc_backoff_base_s", 0.02),
            cap=opt.get_real("rpc_backoff_cap_s", 0.5),
            seed=seed)
        self._sleep = sleep
        self._seq = 0
        self._lock = threading.Lock()
        self.host_index = int(getattr(transport, "host_index", -1))

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def _idem(self, method: str, seq: int) -> str:
        return f"c{id(self):x}.{method}.{seq}"

    def call(self, method: str, payload=None, *,
             deadline: float | None = None,
             idem_key: str | None = None):
        """One logical call: up to ``retry_max`` send attempts of the
        SAME idempotency key under one ``deadline`` budget. Raises
        :class:`RpcDeadlineError` when the budget runs out,
        :class:`TransportUnreachableError` when attempts are exhausted
        with budget left (the host is gone, not slow), or the
        marshalled handler exception itself."""
        budget = self.deadline if deadline is None else float(deadline)
        seq0 = self._next_seq()
        idem = idem_key if idem_key else self._idem(method, seq0)
        t0 = time.perf_counter()
        attempts = 0
        last_exc = None
        with _telemetry.span("rpc.call", method=method,
                             host=self.host_index) as sp:
            while attempts < self.retry_max:
                remaining = budget - (time.perf_counter() - t0)
                if remaining <= 0.0:
                    break
                attempts += 1
                if attempts > 1:
                    _metrics.registry.counter("rpc.retries").inc(
                        label=method)
                msg = Message(kind="request", method=method,
                              seq=self._next_seq(), idem=idem,
                              payload=payload, host=self.host_index)
                try:
                    reply = self.transport.call_once(msg, timeout=remaining)
                except TransportUnreachableError as e:
                    last_exc = e
                    remaining = budget - (time.perf_counter() - t0)
                    if attempts < self.retry_max and remaining > 0.0:
                        self._sleep(min(self.schedule.delay(attempts),
                                        max(0.0, remaining)))
                    continue
                sp.set_attrs(attempts=attempts)
                _metrics.registry.histogram("rpc.call_seconds").observe(
                    time.perf_counter() - t0)
                if reply.error is not None:
                    raise reply.error
                return reply.payload
            sp.set_attrs(attempts=attempts, failed=True)
        _metrics.registry.histogram("rpc.call_seconds").observe(
            time.perf_counter() - t0)
        if time.perf_counter() - t0 >= budget:
            raise RpcDeadlineError(method, self.host_index, attempts,
                                   budget) from last_exc
        raise TransportUnreachableError(
            f"RPC {method!r} to host {self.host_index}: "
            f"{self.retry_max} attempt(s) exhausted "
            f"({last_exc})") from last_exc
