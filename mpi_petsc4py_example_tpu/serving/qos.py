"""QoS-aware scheduling for the solve fleet (serving/server.py + fleet.py).

Under healthy capacity FIFO coalescing is fine: every request dispatches
within a window or two. Under DEGRADED capacity (a shrunk mesh after a
device loss — resilience/elastic.py) one bulk batch job can starve a
p99-sensitive request for seconds, which is exactly when the p99 matters
most. This module adds the three degraded-mode disciplines, all as PURE
host logic (the serving layer's coalescer.py convention — no threads, no
device work, unit-testable in isolation):

* **priority + deadline classes** — :class:`QoSClass` gives a request a
  priority tier and a default dispatch deadline. Two classes ship
  in-tree (``interactive``: tier 0, ``bulk``: tier 100); unlabeled
  requests sit between them, so existing single-class traffic keeps its
  exact FIFO behavior while labeled traffic sorts around it.
* **deadline-weighted scheduling** — :func:`schedule` groups a queue
  snapshot with the same compatibility semantics as
  :func:`~.coalescer.coalesce` (same operator/tolerances/precision —
  NEVER mixed), then orders the batches by urgency: priority tier
  first, earliest effective deadline second, arrival third. The
  dispatcher dispatches ONE batch per pass and re-snapshots, so a
  high-priority arrival preempts the remaining bulk batches INTO THE
  NEXT WINDOW — an in-flight block is never interrupted (preemption is
  a scheduling decision, not a cancellation).
* **priority shedding** — :func:`shed_victim`: with the admission queue
  full, an arriving request may displace the LEAST urgent strictly-
  lower-priority pending request; the victim's future RESOLVES with the
  typed :class:`~..utils.errors.ServerOverloadedError` (``shed=True``)
  — bulk sheds before interactive, and nothing is ever silently
  dropped or left hanging.

On top rides :class:`AutoscalePolicy`: the queue-wait percentiles
``SolveServer.stats()`` already measures (the registry
``Histogram.summary`` path) drive grow / shrink / rebalance decisions
that the :class:`~.fleet.SolveRouter` executes — the policy only ever
DECIDES (pure, testable on synthetic stats); the router owns execution.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.options import global_options

#: priority tier for requests submitted without a QoS class: between
#: interactive (0) and bulk (100), so unlabeled traffic neither starves
#: behind bulk nor outranks explicitly interactive requests
DEFAULT_PRIORITY = 50


@dataclass(frozen=True)
class QoSClass:
    """One service class: a priority tier (LOWER is more urgent) and a
    default per-request dispatch deadline in seconds (0 = none) applied
    when the submission names the class without its own deadline."""
    name: str
    priority: int
    deadline: float = 0.0
    description: str = ""


def builtin_classes() -> dict[str, QoSClass]:
    """The in-tree class table, with the per-class deadline defaults
    overridable at runtime (``-qos_interactive_deadline`` /
    ``-qos_bulk_deadline``)."""
    opt = global_options()
    return {
        "interactive": QoSClass(
            "interactive", 0,
            deadline=opt.get_real("qos_interactive_deadline", 0.0),
            description="p99-sensitive; preempts bulk at window "
                        "boundaries, shed last"),
        "bulk": QoSClass(
            "bulk", 100,
            deadline=opt.get_real("qos_bulk_deadline", 0.0),
            description="throughput batch traffic; yields windows to "
                        "interactive, shed first under overload"),
    }


def default_class_name() -> str:
    """The class assumed for unlabeled submissions
    (``-qos_default_class``; empty keeps them at the neutral
    mid-priority tier)."""
    return str(global_options().get_string("qos_default_class", "") or "")


def resolve(qos: str | None,
            classes: dict[str, QoSClass]) -> QoSClass | None:
    """The :class:`QoSClass` for a submission's ``qos=`` label (or the
    configured default class when unlabeled); None for neutral traffic.
    Unknown labels raise — a typo'd class must not silently demote a
    p99-sensitive request to the neutral tier."""
    name = qos if qos is not None else default_class_name()
    if not name:
        return None
    try:
        return classes[name]
    except KeyError:
        raise ValueError(
            f"unknown QoS class {name!r}; known: {sorted(classes)}"
        ) from None


# --------------------------------------------------------------- scheduling
def _batch_urgency(batch):
    """Sort key of one compatible batch: (best priority tier of its
    members, earliest effective deadline, oldest arrival). A single
    urgent member promotes its whole batch — its batch-mates ride the
    same launch for free, they never delay it."""
    prio = min(r.priority for r in batch)
    deadline = min((r.t_deadline for r in batch
                    if r.t_deadline is not None), default=float("inf"))
    return (prio, deadline, min(r.t_submit for r in batch))


def schedule(requests, max_k: int):
    """Group ``requests`` into dispatchable batches, urgency-ordered.

    Grouping semantics are EXACTLY :func:`~.coalescer.coalesce` —
    compatibility keys never mix, FIFO within a group, ``max_k``
    chunking — the only change is the order BETWEEN batches:
    deadline-weighted priority instead of oldest-member. With uniform
    priorities and no deadlines the sort key degenerates to
    oldest-member, so single-class traffic dispatches byte-identically
    to the pre-QoS coalescer (the stability the serving tests pin).
    """
    from .coalescer import coalesce
    batches = coalesce(requests, max_k)
    batches.sort(key=_batch_urgency)
    return batches


def shed_victim(pending, priority: int):
    """The pending request an arrival of ``priority`` may displace when
    the admission queue is full: the LEAST urgent strictly-lower-
    priority request (highest tier number; newest arrival breaks ties —
    it has lost the least queueing investment). None when nothing
    pending is strictly less urgent — equal-priority arrivals are
    rejected, never each other's victims (no shed cascades)."""
    worst = None
    for r in pending:
        if r.priority <= priority:
            continue
        if (worst is None or r.priority > worst.priority
                or (r.priority == worst.priority
                    and r.t_submit > worst.t_submit)):
            worst = r
    return worst


# --------------------------------------------------------------- autoscale
@dataclass(frozen=True)
class ScaleDecision:
    """One autoscale verdict: ``action`` in {hold, grow, shrink,
    rebalance}; ``replica`` names the shrink target or the
    (busiest, idlest) rebalance pair; ``reason`` is the human-readable
    evidence line the router logs."""
    action: str
    replica: object = None
    reason: str = ""


@dataclass
class AutoscalePolicy:
    """Queue-wait-driven replica scaling policy (decisions only).

    Driven by the per-replica ``queue_wait_p99_s`` the servers already
    measure: sustained p99 above ``high_p99_s`` on any replica asks for
    a GROW (more replicas = fewer sessions per replica after the
    consistent-hash re-spread); p99 below ``low_p99_s`` on EVERY
    replica asks for a SHRINK down to ``min_replicas``; a busiest/idlest
    p99 ratio above ``rebalance_ratio`` (with neither bound tripped)
    asks for one session MIGRATION instead — placement skew, not
    capacity, is the problem there. Replicas with no wait samples yet
    are neutral: they neither trigger growth nor veto a shrink.
    """
    enabled: bool = True
    high_p99_s: float = 0.5
    low_p99_s: float = 0.01
    min_replicas: int = 1
    max_replicas: int = 8
    rebalance_ratio: float = 10.0

    @classmethod
    def from_options(cls) -> "AutoscalePolicy":
        """Policy from the runtime options DB (``-autoscale_*``)."""
        opt = global_options()
        p = cls()
        p.enabled = opt.get_bool("autoscale_enable", p.enabled)
        p.high_p99_s = opt.get_real("autoscale_high_p99", p.high_p99_s)
        p.low_p99_s = opt.get_real("autoscale_low_p99", p.low_p99_s)
        p.min_replicas = opt.get_int("autoscale_min_replicas",
                                     p.min_replicas)
        p.max_replicas = opt.get_int("autoscale_max_replicas",
                                     p.max_replicas)
        p.rebalance_ratio = opt.get_real("autoscale_rebalance_ratio",
                                         p.rebalance_ratio)
        return p

    def decide(self, replica_stats: dict) -> ScaleDecision:
        """``replica_stats``: replica name -> its ``SolveServer.stats()``
        dict. Returns exactly one :class:`ScaleDecision`."""
        if not self.enabled or not replica_stats:
            return ScaleDecision("hold", reason="autoscale disabled"
                                 if not self.enabled else "no replicas")
        p99 = {name: st.get("queue_wait_p99_s")
               for name, st in replica_stats.items()}
        sampled = {n: v for n, v in p99.items() if v is not None}
        n = len(replica_stats)
        hot = [nm for nm, v in sampled.items() if v > self.high_p99_s]
        if hot and n < self.max_replicas:
            worst = max(hot, key=lambda nm: sampled[nm])
            return ScaleDecision(
                "grow", reason=f"replica {worst!r} queue-wait p99 "
                f"{sampled[worst] * 1e3:.1f} ms > "
                f"{self.high_p99_s * 1e3:.1f} ms high watermark")
        if sampled and not hot:
            busiest = max(sampled, key=sampled.get)
            idlest = min(sampled, key=sampled.get)
            if (sampled[idlest] > 0
                    and sampled[busiest] / sampled[idlest]
                    > self.rebalance_ratio):
                return ScaleDecision(
                    "rebalance", replica=(busiest, idlest),
                    reason=f"p99 skew {sampled[busiest] * 1e3:.1f} ms "
                    f"({busiest!r}) vs {sampled[idlest] * 1e3:.1f} ms "
                    f"({idlest!r}) exceeds ratio {self.rebalance_ratio}")
            if (n > self.min_replicas
                    and all(v < self.low_p99_s for v in sampled.values())):
                return ScaleDecision(
                    "shrink", replica=idlest,
                    reason=f"every replica under the "
                    f"{self.low_p99_s * 1e3:.1f} ms low watermark "
                    f"(idlest: {idlest!r})")
        return ScaleDecision("hold", reason="within watermarks")
