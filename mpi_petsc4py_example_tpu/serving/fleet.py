"""Solve fleet: a replica router in front of N SolveServers.

One :class:`~.server.SolveServer` amortizes dispatch latency; a FLEET of
them is the "millions of users" shape (ROADMAP item 2): sessions
(registered operators) are SHARDED across replicas by consistent-hash
placement, a replica loss or rebalance MIGRATES sessions through the
mesh-portable elastic checkpoint format, and the per-replica queue-wait
percentiles drive an autoscale policy (serving/qos.py) whose grow /
shrink / rebalance decisions the router executes. The guiding idea is
the stale-tolerant-replica framing of the two-stage multisplitting
literature (PAPERS.md): a lost or degraded replica is a ROUTING event,
not an outage — traffic re-flows, state re-places, capacity re-grows.

* **Placement** — :class:`HashRing`: each replica contributes
  ``-fleet_vnodes`` virtual points (stable md5 hashes — NEVER Python's
  salted ``hash()``: placement must survive process restarts) and a
  session lands on the first point clockwise of its own hash. Adding or
  removing a replica moves only the sessions whose owning arc changed —
  ~1/N of them — so scaling the fleet re-places the minimum state.
* **Migration** — :meth:`SolveRouter.migrate`: drain the source
  replica's in-flight blocks, checkpoint the session's operator state
  through :mod:`..utils.checkpoint` (the SAME elastic format the
  shrink/re-grow ladder reshards through — it never encoded a mesh
  size, so source and destination replicas may run different
  geometries), re-register on the destination, replay the submissions
  that arrived mid-migration. Every held future resolves with its
  replayed result — clients never observe the move beyond latency.
* **QoS + autoscale** — submissions carry class labels straight through
  to the owning replica's scheduler; :meth:`SolveRouter.autoscale_step`
  feeds per-replica stats to the :class:`~.qos.AutoscalePolicy` and
  executes the decision (span ``fleet.scale``).
* **Heal** — :meth:`SolveRouter.heal_check` asks every degraded replica
  to re-grow onto healed devices (the serving twin of the elastic
  ladder's upward direction).

The router is deliberately a PROCESS-LOCAL front-end object: replicas
are in-process ``SolveServer`` instances (multi-host transports would
wrap the same placement/migration logic around RPC stubs — the routing
and state-movement semantics live here, not in a network layer).
"""

from __future__ import annotations

import bisect
import hashlib
import os
import tempfile
import threading
import time
from concurrent.futures import Future

from ..telemetry import metrics as _metrics
from ..telemetry import spans as _telemetry
from ..utils.options import global_options
from ..utils.profiling import record_migration
from . import qos as _qos
from .server import SolveServer


def _stable_hash(key: str) -> int:
    """64-bit stable hash — placement must be identical across processes
    and restarts (Python's builtin ``hash`` is salted per process)."""
    return int.from_bytes(
        hashlib.md5(key.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over replica names (pure, unit-testable).

    ``vnodes`` virtual points per replica smooth the arc distribution;
    lookup is a binary search over the sorted point list. The stability
    contract the fleet tests pin: a membership change re-places ONLY the
    keys whose owning arc the change touched."""

    def __init__(self, replicas=(), vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._points: list[tuple[int, str]] = []
        self._replicas: set[str] = set()
        for r in replicas:
            self.add(r)

    def add(self, replica: str):
        if replica in self._replicas:
            raise ValueError(f"replica {replica!r} already on the ring")
        self._replicas.add(replica)
        for v in range(self.vnodes):
            self._points.append((_stable_hash(f"{replica}#{v}"), replica))
        self._points.sort()
        return self

    def remove(self, replica: str):
        if replica not in self._replicas:
            raise ValueError(f"replica {replica!r} not on the ring")
        self._replicas.discard(replica)
        self._points = [p for p in self._points if p[1] != replica]
        return self

    def replicas(self):
        return sorted(self._replicas)

    def owner(self, key: str) -> str:
        """The replica owning ``key``: first ring point clockwise of the
        key's hash (wrapping)."""
        if not self._points:
            raise ValueError("empty hash ring (no replicas)")
        h = _stable_hash(str(key))
        i = bisect.bisect_right(self._points, (h, "￿"))
        if i >= len(self._points):
            i = 0
        return self._points[i][1]

    def __len__(self):
        return len(self._replicas)


class SolveRouter:
    """Shard solve sessions across N server replicas (module doc).

    Parameters (``-fleet_*`` runtime flags override, PETSc precedence):

    replicas
        Initial replica count (``-fleet_replicas``).
    vnodes
        Virtual ring points per replica (``-fleet_vnodes``).
    server_factory
        Zero-arg callable building one :class:`SolveServer`; defaults
        to ``SolveServer(comm, **server_kw)``. Process-local replicas
        share the device mesh — a multi-host deployment supplies a
        factory binding each replica to its own hosts.
    autoscale
        An :class:`~.qos.AutoscalePolicy` (default: the
        ``-autoscale_*`` flags). Decisions only execute through
        :meth:`autoscale_step` — the router never scales behind the
        caller's back.
    """

    def __init__(self, replicas: int | None = None, comm=None, *,
                 vnodes: int | None = None, server_factory=None,
                 autoscale: _qos.AutoscalePolicy | None = None,
                 **server_kw):
        opt = global_options()
        n = opt.get_int("fleet_replicas",
                        2 if replicas is None else int(replicas))
        self.vnodes = opt.get_int("fleet_vnodes",
                                  64 if vnodes is None else int(vnodes))
        self._factory = (server_factory
                         or (lambda: SolveServer(comm, **server_kw)))
        self.autoscale = autoscale or _qos.AutoscalePolicy.from_options()
        self._lock = threading.RLock()
        # serializes session MOVES and membership changes against each
        # other (migrate vs add/remove_replica racing on one op) while
        # the router lock stays free during a move's heavy steps —
        # submissions keep flowing (held for the moving op, routed
        # normally for the rest). Order: _move_lock before _lock, never
        # the reverse.
        self._move_lock = threading.Lock()
        self._replicas: dict[str, SolveServer] = {}
        self._ring = HashRing(vnodes=self.vnodes)
        self._serial = 0
        # op -> dict(operator=..., kwargs=...): the registration spec a
        # migration replays on the destination replica
        self._ops: dict[str, dict] = {}
        # op -> replica name: where the session ACTUALLY lives — the
        # authoritative routing table. The ring (+ overrides) only
        # expresses DESIRED placement; keeping the two separate means a
        # failed move leaves routing truthful (the session still serves
        # where it is) instead of pointing at a replica that never got
        # it.
        self._placement: dict[str, str] = {}
        # autoscale rebalance overrides: op -> replica name, consulted
        # before the ring when computing desired placement
        self._overrides: dict[str, str] = {}
        self._migrating: set[str] = set()
        self._held: dict[str, list] = {}
        self._closed = False
        for _ in range(max(1, n)):
            self._add_replica_locked()

    # ---- replica membership -------------------------------------------------
    def _new_name(self) -> str:
        name = f"r{self._serial}"
        self._serial += 1
        return name

    def _add_replica_locked(self) -> str:
        name = self._new_name()
        self._replicas[name] = self._factory()
        self._ring.add(name)
        _metrics.registry.gauge("fleet.replicas").set(len(self._replicas))
        return name

    def replicas(self):
        with self._lock:
            return self._ring.replicas()

    def replica(self, name: str) -> SolveServer:
        with self._lock:
            return self._replicas[name]

    def owner(self, op: str) -> str:
        """The replica ACTUALLY serving ``op`` (the placement table —
        truthful even while a desired-placement move is pending or
        failed)."""
        with self._lock:
            if op not in self._ops:
                raise ValueError(f"unknown operator {op!r}; registered: "
                                 f"{sorted(self._ops)}")
            return self._placement[op]

    def _desired(self, op: str) -> str:
        """Where the ring (+ rebalance overrides) says ``op`` should
        live (lock held)."""
        return self._overrides.get(op) or self._ring.owner(op)

    def _reconcile_locked(self):
        """Move every session whose actual placement differs from its
        desired placement (lock held). A per-op move failure propagates
        AFTER the remaining ops were attempted — placement stays
        truthful for every op either way."""
        errors = []
        for op in sorted(self._ops):
            dst = self._desired(op)
            src = self._placement[op]
            if src == dst:
                continue
            try:
                self._move_session(op, src, dst)
            # tpslint: disable=TPS005 — one session that cannot move
            # must not strand the others mid-membership-change; the
            # collected error re-raises below with routing still
            # truthful (the op keeps serving where it is)
            except Exception as exc:  # noqa: BLE001
                errors.append((op, exc))
        if errors:
            raise RuntimeError(
                f"fleet reconcile: {len(errors)} session move(s) failed "
                f"({', '.join(op for op, _ in errors)}); routing remains "
                "on the source replicas") from errors[0][1]

    def add_replica(self) -> str:
        """Grow the fleet by one replica; sessions whose owning arc the
        new replica took over migrate to it (the consistent-hash
        minimum — ~1/N of the sessions, the rest stay put)."""
        with self._move_lock:
            with self._lock:
                name = self._add_replica_locked()
                self._reconcile_locked()
                return name

    def remove_replica(self, name: str):
        """Drain one replica out of the fleet: its sessions migrate to
        their new ring owners, then the emptied server shuts down. A
        failed move aborts the removal (ring membership restored) with
        every session still routed where it actually lives."""
        with self._move_lock:
            with self._lock:
                if len(self._replicas) <= 1:
                    raise ValueError("cannot remove the last replica")
                srv = self._replicas[name]   # KeyError: unknown replica
                saved_overrides = dict(self._overrides)
                self._ring.remove(name)
                # overrides pinned to the leaving replica dissolve back
                # to the ring
                self._overrides = {op: r
                                   for op, r in self._overrides.items()
                                   if r != name}
                try:
                    self._reconcile_locked()
                # tpslint: disable=TPS005 — rollback-and-reraise,
                # nothing swallowed: whatever reconcile raised must
                # abort the removal (ring membership restored first)
                # and still reach the caller
                except Exception:  # noqa: BLE001
                    self._ring.add(name)
                    self._overrides = saved_overrides
                    raise
                del self._replicas[name]
                _metrics.registry.gauge("fleet.replicas").set(
                    len(self._replicas))
        srv.shutdown(wait=True)

    # ---- session registry ---------------------------------------------------
    def register_operator(self, name: str, A, **kw):
        """Register ``name`` on its consistent-hash owner replica; the
        registration spec is retained so migrations can re-register the
        session elsewhere (same kwargs, checkpoint-reloaded operator)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("SolveRouter is shut down")
            if name in self._ops:
                raise ValueError(f"operator {name!r} already registered")
            owner = self._ring.owner(name)
            sess = self._replicas[owner].register_operator(name, A, **kw)
            # only now (registration succeeded) does the op enter the
            # routing tables; retain the PLACED operator (not the
            # caller's raw A): a migration checkpoint needs
            # to_scipy/with_comm, which the framework operator has and
            # a raw scipy matrix may not
            self._ops[name] = {"kwargs": dict(kw),
                               "operator": sess.operator}
            self._placement[name] = owner
            return sess

    def operators(self):
        with self._lock:
            return sorted(self._ops)

    # ---- client APIs --------------------------------------------------------
    def submit(self, op: str, b, **kw) -> Future:
        """Route one solve to ``op``'s owner replica (QoS/tolerance
        kwargs pass straight through to ``SolveServer.submit``). While
        ``op`` is mid-migration the submission is HELD and replayed on
        the destination — the returned future resolves either way."""
        with self._lock:
            if self._closed:
                raise RuntimeError("SolveRouter is shut down")
            owner = self.owner(op)
            if op in self._migrating:
                fut: Future = Future()
                self._held.setdefault(op, []).append((b, dict(kw), fut))
                return fut
            return self._replicas[owner].submit(op, b, **kw)

    def solve(self, op: str, b, *, timeout: float | None = None, **kw):
        """Synchronous client API: submit + wait."""
        return self.submit(op, b, **kw).result(timeout)

    # ---- migration ----------------------------------------------------------
    def migrate(self, op: str, dst: str):
        """Move session ``op`` to replica ``dst`` (drain -> checkpoint
        -> re-register -> replay; module doc). Pins an override so the
        placement survives ring lookups until membership changes it.

        The source drain runs OUTSIDE the router lock, so submissions
        arriving mid-migration are HELD (``submit`` queues them) and
        replayed once the session lands — clients never observe the
        move beyond latency. On failure the override rolls back, the
        session keeps serving on the source, and every held future is
        still replayed there — resolved, never orphaned."""
        with self._move_lock:
            self._migrate_impl(op, dst)

    def _migrate_impl(self, op: str, dst: str):
        with self._lock:
            src = self.owner(op)
            if src == dst:
                return
            if dst not in self._replicas:
                raise ValueError(f"unknown replica {dst!r}")
            prev = self._overrides.get(op)
            self._overrides[op] = dst
            self._migrating.add(op)
            src_srv = self._replicas[src]
        moved = False
        try:
            # drain THIS session's queue with the router lock RELEASED:
            # new arrivals for it go to the held queue instead of
            # blocking client threads, so its backlog strictly shrinks —
            # and co-resident sessions' sustained traffic cannot
            # livelock the move (drain_operator ignores them). The move
            # itself also runs outside the router lock (the op is
            # guarded by _migrating + the move lock): its
            # session-lock wait for an in-flight source block must not
            # stall submissions to every other session.
            src_srv.drain_operator(op)
            self._move_session(op, src, dst)
            moved = True
        finally:
            with self._lock:
                self._migrating.discard(op)
                if not moved:
                    # roll the desired placement back — the session
                    # still serves on the source
                    if prev is None:
                        self._overrides.pop(op, None)
                    else:
                        self._overrides[op] = prev
                landed = self._replicas[self._placement[op]]
                held = self._held.pop(op, [])
            # replay wherever the session actually lives now (the
            # destination on success, the source on a rolled-back
            # failure) — every held future resolves either way
            for b, kw, outer in held:
                try:
                    _chain_future(landed.submit(op, b, **kw), outer)
                # tpslint: disable=TPS005 — a replay that cannot even
                # submit must still RESOLVE the held future (typed
                # error), never leave a client hanging
                except Exception as exc:  # noqa: BLE001
                    if outer.set_running_or_notify_cancel():
                        outer.set_exception(exc)

    def _move_session(self, op: str, src: str, dst: str):
        """The migration engine (move lock held; the ROUTER lock is
        only taken for the brief table reads/writes, so a move's heavy
        steps — checkpoint, destination compile, the session-lock wait
        for an in-flight source block — never stall unrelated
        submissions). Exception-safe ordering: the destination session
        is fully registered BEFORE the source one is unregistered, so a
        failure at any step leaves the session serving somewhere and
        ``_placement`` truthful."""
        from ..utils.checkpoint import (load_solve_state_many,
                                        save_solve_state_many)
        import numpy as np
        with self._lock:
            src_srv, dst_srv = self._replicas[src], self._replicas[dst]
            spec = self._ops[op]
        t0 = time.perf_counter()
        path = os.path.join(
            tempfile.gettempdir(),
            f"tpu_solve_migrate_{os.getpid()}_{op}.npz")
        try:
            with _telemetry.span("fleet.migrate", op=op, src=src,
                                 dst=dst) as msp:
                # 1. drain this session's queue (idempotent if migrate()
                # already drained outside the lock; membership changes
                # hold the router lock so no new arrivals race it)
                src_srv.drain_operator(op)
                # 2. checkpoint through the elastic format: the operator
                # state becomes mesh-portable bytes (a drained session
                # has no live iterate block — the zero block below keeps
                # the format's schema; a preemptive mid-solve migration
                # would carry the real partial block the same way)
                mat = spec["operator"]
                n = int(mat.shape[0])
                z = np.zeros((n, 1), dtype=np.dtype(mat.dtype))
                save_solve_state_many(path, mat, z, z, iteration=0)
                # 3. register on the destination from the reloaded
                # (destination-mesh-placed) operator — the source
                # session is still live: a failure up to here changes
                # nothing
                mat2, _X, _B, _it = load_solve_state_many(
                    path, dst_srv.comm)
                dst_srv.register_session(op, mat2, **spec["kwargs"])
                # 4. the destination is live — only now depart the
                # source and flip the authoritative placement. If the
                # departure fails (an out-of-contract direct-to-server
                # submission still pending), UNDO the destination
                # registration: a failed move must leave exactly one
                # live session, on the source, or the op can never be
                # retried onto this replica ('already registered').
                try:
                    src_srv.unregister_operator(op)
                # tpslint: disable=TPS005 — compensate-and-reraise:
                # nothing swallowed, the dst orphan is removed and the
                # original departure failure still reaches the caller
                except Exception:  # noqa: BLE001
                    dst_srv.unregister_operator(op)
                    raise
                with self._lock:
                    spec["operator"] = mat2
                    self._placement[op] = dst
                msp.set_attrs(wall_s=time.perf_counter() - t0)
        finally:
            try:
                os.remove(path)
            except OSError:
                pass
        record_migration(op, src, dst, time.perf_counter() - t0)

    def rehome(self, op: str, dst: str):
        """Out-of-band placement flip (serving/remote.py): a failover
        or post-partition reconcile has ALREADY re-registered ``op`` on
        ``dst`` — from its last elastic checkpoint, outside the
        router's own migration engine — so only the routing tables
        move. Pins an override so ring lookups keep the session where
        the failure detector put it until membership changes it."""
        with self._lock:
            if op not in self._ops:
                raise ValueError(f"unknown operator {op!r}; registered: "
                                 f"{sorted(self._ops)}")
            if dst not in self._replicas:
                raise ValueError(f"unknown replica {dst!r}")
            self._placement[op] = dst
            self._overrides[op] = dst

    # ---- autoscale / heal ---------------------------------------------------
    def autoscale_step(self) -> _qos.ScaleDecision:
        """One policy evaluation + execution: collect per-replica stats,
        ask the :class:`~.qos.AutoscalePolicy`, execute the decision
        (grow -> :meth:`add_replica`; shrink -> :meth:`remove_replica`;
        rebalance -> migrate ONE session from the busiest to the idlest
        replica). Returns the decision (action 'hold' executes
        nothing)."""
        with self._lock:
            stats = {name: srv.stats()
                     for name, srv in self._replicas.items()}
        decision = self.autoscale.decide(stats)
        _metrics.registry.counter("fleet.scale_decisions").inc(
            label=decision.action)
        if decision.action == "hold":
            return decision
        with _telemetry.span("fleet.scale", action=decision.action,
                             reason=decision.reason) as ssp:
            if decision.action == "grow":
                ssp.set_attr("replica", self.add_replica())
            elif decision.action == "shrink":
                self.remove_replica(decision.replica)
                ssp.set_attr("replica", decision.replica)
            elif decision.action == "rebalance":
                busiest, idlest = decision.replica
                moved = None
                with self._lock:
                    for op in sorted(self._ops):
                        if self.owner(op) == busiest:
                            moved = op
                            break
                if moved is not None:
                    self.migrate(moved, idlest)
                ssp.set_attrs(op=moved or "", src=busiest, dst=idlest)
        return decision

    def heal_check(self) -> int:
        """Ask every degraded replica to re-grow onto healed devices
        (:meth:`SolveServer.regrow`); returns how many re-grew. The
        routing twin of the dispatcher's own heal-epoch check — a
        driver that KNOWS a repair happened calls this for immediate
        capacity instead of waiting for each replica's next window."""
        with self._lock:
            servers = list(self._replicas.values())
        # regrow() is thread-safe: the server's session lock makes the
        # rebuild wait out any in-flight dispatch instead of swapping
        # operators under it
        return sum(1 for srv in servers if srv.regrow())

    # ---- observability / lifecycle ------------------------------------------
    def stats(self) -> dict:
        """Fleet-level aggregate + the per-replica stats() dicts."""
        with self._lock:
            per = {name: srv.stats()
                   for name, srv in self._replicas.items()}
            placement = {op: self.owner(op) for op in self._ops}
        agg = {"replicas": len(per),
               "requests": sum(s["requests"] for s in per.values()),
               "batches": sum(s["batches"] for s in per.values()),
               "shed": sum(s["shed"] for s in per.values()),
               "rejected": sum(s["rejected"] for s in per.values()),
               "mesh_shrinks": sum(len(s["mesh_shrinks"])
                                   for s in per.values()),
               "mesh_regrows": sum(len(s["mesh_regrows"])
                                   for s in per.values()),
               "placement": placement,
               "per_replica": per}
        return agg

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every replica's queue flushed; False on
        timeout."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._lock:
            servers = list(self._replicas.values())
        for srv in servers:
            rem = (None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
            if not srv.drain(rem):
                return False
        return True

    def shutdown(self, wait: bool = True):
        """Shut every replica down (``wait`` as in
        :meth:`SolveServer.shutdown`: True flushes queues first)."""
        with self._lock:
            self._closed = True
            servers = list(self._replicas.values())
        for srv in servers:
            srv.shutdown(wait=wait)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(wait=exc == (None, None, None))
        return False

    def __repr__(self):
        with self._lock:
            return (f"SolveRouter(replicas={self._ring.replicas()}, "
                    f"ops={sorted(self._ops)})")


def _chain_future(inner: Future, outer: Future):
    """Resolve ``outer`` with whatever ``inner`` resolves to — the
    replay bridge for submissions held across a migration."""
    def _done(f: Future):
        if f.cancelled():
            outer.cancel()
            return
        if not outer.set_running_or_notify_cancel():
            return
        exc = f.exception()
        if exc is not None:
            outer.set_exception(exc)
        else:
            outer.set_result(f.result())
    inner.add_done_callback(_done)
