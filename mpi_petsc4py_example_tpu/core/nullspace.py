"""MatNullSpace — singular-operator support (PETSc MatNullSpace analog).

PETSc workflows attach a null space to the matrix (``MatSetNullSpace``) so
Krylov solvers converge on *compatible* singular systems — the canonical case
being the pure-Neumann / periodic Poisson operator whose null space is the
constant vector. The reference reaches this machinery through petsc4py
[external]; here the projection happens inside the jit-compiled shard_map
Krylov program: the RHS/initial guess and every operator/preconditioner
output get their null-space component removed with one fused ``psum`` dot per
basis vector (see solvers/krylov.py).

The basis is orthonormalized on host (QR) once and stored replicated-free as
a row-sharded ``(k, n_pad)`` device array.
"""

from __future__ import annotations

import numpy as np


class NullSpace:
    """petsc4py-``NullSpace``-shaped: ``create(constant=..., vectors=...)``."""

    def __init__(self, constant: bool = False, vectors=()):
        self._constant = bool(constant)
        self._vectors = [np.asarray(getattr(v, "to_numpy", lambda: v)())
                         for v in vectors]
        self._built = None      # (comm, n, dtype) -> Q cache

    @classmethod
    def create(cls, constant: bool = False, vectors=(), comm=None):
        """``comm`` is accepted for petsc4py shape compatibility (the mesh
        communicator is taken from the matrix at solve time)."""
        return cls(constant=constant, vectors=vectors)

    @property
    def dim(self) -> int:
        return int(self._constant) + len(self._vectors)

    def has_constant(self) -> bool:
        return self._constant

    hasConstant = has_constant

    def basis_host(self, n: int) -> np.ndarray:
        """Orthonormal (k, n) host basis of the null space."""
        cols = []
        if self._constant:
            cols.append(np.ones(n))
        for v in self._vectors:
            if v.shape[0] != n:
                raise ValueError(
                    f"null-space vector has length {v.shape[0]}, matrix "
                    f"needs {n}")
            cols.append(np.asarray(v, dtype=np.float64))
        if not cols:
            raise ValueError("empty null space: pass constant=True and/or "
                             "vectors")
        Q, R = np.linalg.qr(np.stack(cols, axis=1))
        if np.any(np.abs(np.diag(R)) < 1e-12 * max(1.0, np.abs(R).max())):
            raise ValueError("null-space vectors are linearly dependent")
        return Q.T

    def device_array(self, comm, n: int, dtype):
        """Row-sharded (k, n_pad) orthonormal basis (cached per mesh/size)."""
        from jax.sharding import PartitionSpec as P
        key = (comm.mesh, n, str(np.dtype(dtype)))
        if self._built is not None and self._built[0] == key:
            return self._built[1]
        Q = self.basis_host(n)
        npad = comm.padded_size(n)
        Qp = np.zeros((Q.shape[0], npad), dtype=np.dtype(dtype))
        Qp[:, :n] = Q
        arr = comm.put_spec(Qp, P(None, comm.axis))
        self._built = (key, arr)
        return arr

    def remove(self, v: np.ndarray) -> np.ndarray:
        """Host-side projection (oracle/debug): v minus its null component."""
        Q = self.basis_host(v.shape[0])
        return v - Q.T @ (Q @ v)

    def test(self, mat) -> bool:
        """True if A @ q ≈ 0 for every basis vector (petsc4py ``ns.test``)."""
        A = mat.to_scipy()
        Q = self.basis_host(mat.shape[0])
        r = np.linalg.norm(A @ Q.T, axis=0)
        scale = abs(A).sum() / max(mat.shape[0], 1)
        return bool(np.all(r <= 1e-10 * max(scale, 1.0)))

    def __repr__(self):
        return (f"NullSpace(constant={self._constant}, "
                f"extra_vectors={len(self._vectors)})")
