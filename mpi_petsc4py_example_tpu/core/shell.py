"""Matrix-free shell operators — the PETSc ``MatShell`` equivalent.

PETSc lets drivers supply their own ``MatMult`` through ``MatCreateShell`` /
``MATSHELL`` so Krylov solvers run on operators that are never assembled
(reference capability surface: the KSP/EPS solvers at ``test.py:50`` /
``test2.py:88`` only ever *apply* the operator — SURVEY.md N3/N6). Here a
shell operator is a **jax-traceable function on the full input vector**: the
framework all-gathers the sharded vector inside the compiled shard_map
program, applies the user function on every device, and keeps the local row
block — so a shell operator composes with every KSP/EPS type and
preconditioner exactly like an assembled :class:`~.mat.Mat`.

For operators with sharding-aware structure (e.g. stencils with neighbor
halos) implement the full linear-operator protocol instead, as
``models.stencil.StencilPoisson3D`` does — shell operators trade peak
scalability for zero-boilerplate matrix-free usage.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import DeviceComm, as_comm, full_vector_local_apply
from ..parallel.partition import RowLayout
from .vec import Vec

_uid = itertools.count(1)


class ShellMat:
    """Matrix-free operator defined by a user ``mult`` function.

    Parameters
    ----------
    comm : DeviceComm
    shape : int | (int, int)
        Global operator shape (square — row and column partition coincide).
    mult : callable
        ``y = mult(x)`` on the full (unsharded) global vector; must be
        jax-traceable (jnp ops, no Python control flow on values). It runs
        replicated on every mesh device inside the compiled solver program.
    mult_transpose : callable, optional
        ``y = mult_transpose(x)`` — enables transpose-needing KSP types
        (``lsqr``, ``bicg``, ``cgne``) and unsymmetric eigenproblems.
    diagonal : callable | array, optional
        The operator diagonal (for PC ``jacobi``): an array of length n or a
        zero-argument callable returning one.
    """

    def __init__(self, comm, shape, mult, mult_transpose=None, diagonal=None,
                 dtype=jnp.float64):
        self.comm: DeviceComm = as_comm(comm)
        if np.isscalar(shape):
            shape = (int(shape), int(shape))
        self.shape = (int(shape[0]), int(shape[1]))
        if self.shape[0] != self.shape[1]:
            raise ValueError(
                f"ShellMat must be square (row/column partitions coincide); "
                f"got {self.shape}")
        self._mult = mult
        self._mult_t = mult_transpose
        self._diagonal = diagonal
        self.dtype = jnp.dtype(dtype)
        self.layout = RowLayout(self.shape[0], self.comm.size)
        self._key = ("shellmat", next(_uid))
        self._jit_mult = jax.jit(mult)   # host-level apply, compiled once
        self._jit_mult_t = None          # lazily jitted on first use

    # ---- Mat-shaped conveniences -------------------------------------------
    def get_vecs(self) -> tuple[Vec, Vec]:
        mk = lambda: Vec(self.comm, self.shape[0], dtype=self.dtype,
                         layout=self.layout)
        return mk(), mk()

    getVecs = get_vecs

    def diagonal(self) -> np.ndarray:
        if self._diagonal is None:
            raise ValueError(
                "this ShellMat provides no diagonal — pass diagonal= at "
                "construction to use PC 'jacobi'")
        d = self._diagonal() if callable(self._diagonal) else self._diagonal
        return np.asarray(d)

    def mult(self, x: Vec, y: Vec | None = None) -> Vec:
        """Host-level apply (the solvers use :meth:`local_spmv` instead)."""
        xh = jnp.asarray(x.to_numpy(), dtype=self.dtype)
        yh = np.asarray(self._jit_mult(xh))
        if y is None:
            return Vec.from_global(self.comm, yh, dtype=self.dtype)
        y.set_global(yh)
        return y

    def mult_transpose(self, x: Vec, y: Vec | None = None) -> Vec:
        """Host-level transpose apply (MatMultTranspose for shell operators)."""
        if self._mult_t is None:
            raise ValueError(
                "this ShellMat provides no mult_transpose — pass it at "
                "construction")
        if self._jit_mult_t is None:
            self._jit_mult_t = jax.jit(self._mult_t)
        xh = jnp.asarray(x.to_numpy(), dtype=self.dtype)
        yh = np.asarray(self._jit_mult_t(xh))
        if y is None:
            return Vec.from_global(self.comm, yh, dtype=self.dtype)
        y.set_global(yh)
        return y

    multTranspose = mult_transpose

    # ---- linear-operator protocol (consumed by solvers.krylov/eps) ----------
    def device_arrays(self):
        return ()

    def op_specs(self, axis):
        return ()

    def program_key(self):
        return self._key

    def _wrap(self, fn, comm: DeviceComm):
        apply = full_vector_local_apply(fn, comm, self.shape[0])
        return lambda op_local, x_local: apply(x_local)

    def local_spmv(self, comm: DeviceComm):
        return self._wrap(self._mult, comm)

    def local_spmv_t(self, comm: DeviceComm):
        if self._mult_t is None:
            raise ValueError(
                "this ShellMat provides no mult_transpose — required by "
                "transpose-needing KSP types (lsqr/bicg/cgne)")
        return self._wrap(self._mult_t, comm)

    def __repr__(self):
        return (f"ShellMat(shape={self.shape}, devices={self.comm.size}, "
                f"dtype={self.dtype})")
