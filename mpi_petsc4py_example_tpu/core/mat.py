"""Distributed sparse matrix: row-sharded ELL/CSR in HBM.

TPU-native equivalent of PETSc ``Mat`` (MPIAIJ) — SURVEY.md N1. The reference
constructs it from the contract *(comm, global shape, local rebased-CSR with
global column indices)* (``petsc_funcs.py:5-10``, ``test.py:24``); the
constructors here accept exactly that, plus a whole-matrix convenience path.

Storage: the device layout is ELL (see ops/spmv.py) with rows 1-D sharded
over the mesh — one shard per device, padding rows empty. A host-side scipy
CSR copy is retained when available for preconditioner factorizations
(block-Jacobi / LU) and oracle checks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.spmv import (accum_dtype as _accum, csr_diag,
                        csr_find_diagonals, csr_to_dia, csr_to_ell,
                        dia_spmv_local, dia_spmv_local_many,
                        ell_spmv_local, ell_spmv_local_many)
from ..parallel.mesh import DeviceComm, as_comm
from ..parallel.partition import RowLayout, concat_csr_blocks
from .vec import Vec


class Mat:
    """Row-sharded distributed sparse matrix (AIJ-equivalent)."""

    def __init__(self, comm, shape, ell_cols: jax.Array, ell_vals: jax.Array,
                 host_csr=None, layout: RowLayout | None = None):
        self.comm: DeviceComm = as_comm(comm)
        self.shape = (int(shape[0]), int(shape[1]))
        self.layout = layout or RowLayout(self.shape[0], self.comm.size)
        # (n_pad, K) arrays sharded on axis 0.
        self.ell_cols = ell_cols
        self.ell_vals = ell_vals
        # optional host CSR triple (indptr, indices, data) of the full matrix
        self.host_csr = host_csr
        self._assembled = False
        # bumped by every in-place mutation (axpy/scale/shift/zero_rows) so
        # PC/EPS setup caches keyed on this Mat know to rebuild
        self._state = 0
        # constant-diagonal fast path (set by model generators so Jacobi
        # setup never pulls a 100M-row ELL back to host)
        self._diag_value: float | None = None
        # DIA fast path for banded matrices: (n_pad, D) values + static
        # offsets; SpMV becomes shifted slices instead of a gather
        self.dia_vals: jax.Array | None = None
        self.dia_offsets: tuple[int, ...] = ()

    # ---- constructors ------------------------------------------------------
    @classmethod
    def create_aij(cls, comm, size, csr, dtype=jnp.float64) -> "Mat":
        """The reference contract: global ``size``, *local* rebased CSR.

        In single-controller mode the caller's "local" block is the whole
        matrix when its indptr covers all rows (the ``mpirun -n 1`` path the
        reference supports, ``test.py:77`` empty loop). For true per-rank
        blocks, assemble with :meth:`from_local_blocks`.
        """
        nrows, ncols = size
        indptr, indices, data = csr
        local_rows = len(indptr) - 1
        if local_rows == nrows:
            return cls.from_csr(comm, size, csr, dtype=dtype)
        raise ValueError(
            f"local CSR has {local_rows} rows but global shape is {size}; "
            "assemble per-rank blocks with Mat.from_local_blocks")

    @classmethod
    def from_csr(cls, comm, size, csr, dtype=jnp.float64) -> "Mat":
        """Build from a *global* host CSR triple.

        Validation and the CSR->ELL layout conversion run through the native
        C++ toolkit (native/csrkit.cpp) when available — the role PETSc's C
        MatAssembly plays — with a vectorized-numpy fallback.

        Round 6 (the cfg4 assembly fix): ALL host-side layout work —
        ELL conversion and the DIA detect/convert — runs first, then every
        device array ships in ONE batched placement
        (:meth:`DeviceComm.put_rows_many`), so the runtime's fixed
        per-transfer dispatch cost is paid once, not once per array; the
        placement is synced (``block_until_ready``) before its stamp so
        ``assembly_breakdown`` attributes real time, not async-dispatch
        slack spilled into whatever the caller times next.
        """
        import time as _time

        from ..utils import native
        comm = as_comm(comm)
        nrows, ncols = int(size[0]), int(size[1])
        t0 = _time.perf_counter()
        indptr = np.asarray(csr[0], dtype=np.int64)
        indices = np.asarray(csr[1], dtype=np.int32)
        data = np.asarray(csr[2], dtype=dtype)
        err = native.csr_validate(indptr, indices, ncols)
        if err != 0:
            reasons = {-1: "indptr[0] != 0", -2: "indptr not monotone",
                       -3: "indptr[-1] != nnz", -4: "column index out of range"}
            raise ValueError(f"malformed CSR: {reasons.get(err, err)}")
        t1 = _time.perf_counter()
        # the native C++ conversion handles the machine float families
        # only; ml_dtypes storage (bfloat16, numpy kind 'V') takes the
        # vectorized-numpy path, which is dtype-agnostic
        if (native.available() and len(data) > 1_000_000
                and data.dtype.kind in "fc"):
            cols, vals = native.csr_to_ell_native(indptr, indices, data)
            vals = vals.astype(dtype, copy=False)
        else:
            cols, vals = csr_to_ell(indptr, indices, data)
        K = cols.shape[1]
        t2 = _time.perf_counter()
        # auto-select the DIA layout for banded square matrices: same-order
        # storage as ELL but gather-free SpMV (shifted slices)
        offsets, dia = None, None
        if nrows == ncols:
            offsets = csr_find_diagonals(indptr, indices,
                                         max_diags=max(2 * K, 8))
            # an empty offsets set (all-zero matrix) stays on the ELL path —
            # the DIA kernels assume at least one stored diagonal
            if offsets is not None and 0 < len(offsets) <= max(2 * K, 8):
                dia = csr_to_dia(indptr, indices, data, nrows, offsets)
            else:
                offsets = None
        t3 = _time.perf_counter()
        placed = comm.put_rows_many(
            [cols, vals] + ([dia] if dia is not None else []))
        import jax as _jax
        _jax.block_until_ready(placed)
        t4 = _time.perf_counter()
        m = cls(comm, (nrows, ncols), placed[0], placed[1],
                host_csr=(indptr, indices, data))
        if dia is not None:
            m.dia_vals = placed[2]
            m.dia_offsets = tuple(int(o) for o in offsets)
        m._assembled = True
        # where MatAssembly time goes (BASELINE cfg1/cfg4 ask): validate /
        # ELL conversion / DIA detect+convert / the one synced placement
        m.assembly_breakdown = {
            "validate_s": round(t1 - t0, 4),
            "ell_convert_s": round(t2 - t1, 4),
            "dia_convert_s": round(t3 - t2, 4),
            "device_put_s": round(t4 - t3, 4),
        }
        return m

    @classmethod
    def from_local_blocks(cls, comm, size, blocks, dtype=jnp.float64) -> "Mat":
        """Build from per-rank local CSR blocks (the reference's L5 output)."""
        indptr, indices, data = concat_csr_blocks(blocks)
        return cls.from_csr(comm, size, (indptr, indices, data), dtype=dtype)

    def astype(self, dtype) -> "Mat":
        """An assembled Mat holding the same values in another storage
        dtype — the precision-plan constructor (``RefinedKSP`` builds its
        bf16/f32 inner operator through this; PARITY.md "Mixed
        precision"). Conversion runs from the retained host CSR when
        available (one rounding step from the assembly-precision values,
        not two), falling back to the fetched device layout. The null
        space, if any, rides along.

        NOTE: unlike ``ndarray.astype``, a matching dtype returns
        ``self`` (no copy) — the device operands are immutable on the
        hot paths and a same-dtype rebuild would only churn HBM; use
        :meth:`duplicate` when an independent same-dtype Mat is
        needed."""
        dtype = np.dtype(dtype)
        if dtype == np.dtype(self.dtype):
            return self
        if self.host_csr is not None:
            m = Mat.from_csr(self.comm, self.shape, self.host_csr,
                             dtype=dtype)
        else:
            m = Mat.from_scipy(self.comm, self.to_scipy(), dtype=dtype)
        ns = self.get_nullspace()
        if ns is not None:
            m.set_nullspace(ns)
        return m

    @classmethod
    def from_scipy(cls, comm, A, dtype=jnp.float64) -> "Mat":
        import time as _time
        t0 = _time.perf_counter()
        A = A.tocsr()
        tocsr = _time.perf_counter() - t0
        m = cls.from_csr(comm, A.shape, (A.indptr, A.indices, A.data),
                         dtype=dtype)
        # the format conversion is part of what callers time as assembly —
        # it must appear in the breakdown or the parts can't sum to the wall
        m.assembly_breakdown = {"tocsr_s": round(tocsr, 4),
                                **m.assembly_breakdown}
        return m

    # ---- PETSc-Mat-shaped API ----------------------------------------------
    def set_up(self):
        return self

    def assemble(self):
        self._assembled = True
        return self

    assembly_begin = assemble
    assembly_end = assemble

    @property
    def assembled(self) -> bool:
        return self._assembled

    @property
    def dtype(self):
        return self.ell_vals.dtype

    @property
    def n_pad(self) -> int:
        return self.ell_cols.shape[0]

    @property
    def K(self) -> int:
        """ELL width: max nonzeros per row."""
        return self.ell_cols.shape[1]

    def get_vecs(self) -> tuple[Vec, Vec]:
        """Compatibly-sharded (x, b) pair — the reference's ``a.getVecs()``."""
        mk = lambda: Vec(self.comm, self.shape[0], dtype=self.dtype,
                         layout=self.layout)
        return mk(), mk()

    # ---- null space (PETSc MatSetNullSpace) --------------------------------
    def set_nullspace(self, nullspace):
        """Attach a :class:`core.nullspace.NullSpace`; KSP then projects the
        RHS and all operator/PC outputs onto its complement (the PETSc route
        to compatible singular systems, e.g. pure-Neumann Poisson)."""
        self.nullspace = nullspace
        return self

    setNullSpace = set_nullspace

    def get_nullspace(self):
        return getattr(self, "nullspace", None)

    getNullSpace = get_nullspace

    # ---- assembled-matrix algebra (PETSc Mat API surface) ------------------
    def _replace_from_scipy(self, S):
        """Rebuild this Mat's storage in place from a scipy matrix (PETSc's
        mutating Mat ops rebuild the assembled form the same way)."""
        S = S.tocsr()
        rebuilt = Mat.from_csr(self.comm, S.shape,
                               (S.indptr, S.indices, S.data),
                               dtype=self.dtype)
        self.shape = rebuilt.shape
        self.layout = rebuilt.layout
        self.ell_cols = rebuilt.ell_cols
        self.ell_vals = rebuilt.ell_vals
        self.host_csr = rebuilt.host_csr
        self.dia_vals = rebuilt.dia_vals
        self.dia_offsets = rebuilt.dia_offsets
        self._diag_value = None
        self._assembled = True
        self._state += 1
        return self

    def norm(self, norm_type: str = "frobenius") -> float:
        """Matrix norm: 'frobenius' (PETSc default), '1', or 'inf'."""
        import scipy.sparse.linalg  # noqa: F401  (norm lives on the module)
        import scipy.sparse as sp
        S = self.to_scipy()
        t = str(norm_type).lower()
        if t in ("frobenius", "fro"):
            return float(sp.linalg.norm(S, "fro"))
        if t in ("1", "one"):
            return float(np.abs(S).sum(axis=0).max())
        if t in ("inf", "infinity"):
            return float(np.abs(S).sum(axis=1).max())
        raise ValueError(f"unknown norm type {norm_type!r}")

    def transpose(self) -> "Mat":
        """A new assembled Mat holding A^T."""
        return Mat.from_scipy(self.comm, self.to_scipy().T.tocsr(),
                              dtype=self.dtype)

    def duplicate(self, copy_values: bool = True) -> "Mat":
        S = self.to_scipy().copy()
        if not copy_values:
            S.data[:] = 0.0
        return Mat.from_scipy(self.comm, S, dtype=self.dtype)

    def copy(self) -> "Mat":
        return self.duplicate(copy_values=True)

    def axpy(self, alpha: float, X: "Mat") -> "Mat":
        """Y <- Y + alpha*X (PETSc MatAXPY; rebuilds the device layout)."""
        if X.shape != self.shape:
            raise ValueError(f"axpy shape mismatch: {self.shape} vs {X.shape}")
        return self._replace_from_scipy(
            self.to_scipy() + float(alpha) * X.to_scipy())

    def scale(self, alpha: float) -> "Mat":
        """A <- alpha*A — pure device-side scaling, no host rebuild."""
        alpha = self.dtype.type(alpha)
        self.ell_vals = self.ell_vals * alpha
        if self.dia_vals is not None:
            self.dia_vals = self.dia_vals * alpha
        if self.host_csr is not None:
            ip, ix, dv = self.host_csr
            self.host_csr = (ip, ix, dv * float(alpha))
        if self._diag_value is not None:
            self._diag_value *= float(alpha)
        self._state += 1
        return self

    def shift(self, alpha: float) -> "Mat":
        """A <- A + alpha*I (PETSc MatShift)."""
        import scipy.sparse as sp
        return self._replace_from_scipy(
            self.to_scipy() + float(alpha) * sp.eye(self.shape[0],
                                                    format="csr"))

    def zero_rows(self, rows, diag: float = 1.0, b: Vec | None = None,
                  x: Vec | None = None) -> "Mat":
        """PETSc MatZeroRows: zero the given global rows, put ``diag`` on
        their diagonal, and (given x, b) fix ``b[rows] = diag * x[rows]`` —
        the standard way to impose Dirichlet conditions on an assembled
        system."""
        rows = np.asarray(rows, dtype=np.int64)
        S = self.to_scipy().tolil()
        S[rows, :] = 0.0
        if diag != 0.0:
            S[rows, rows] = diag
        self._replace_from_scipy(S.tocsr())
        if b is not None and x is not None:
            bh = b.to_numpy()
            bh[rows] = diag * x.to_numpy()[rows]
            b.set_global(bh)
        return self

    zeroRows = zero_rows

    def get_row(self, i: int):
        """(cols, vals) of global row i (PETSc MatGetRow)."""
        S = self.to_scipy()
        s, e = int(S.indptr[i]), int(S.indptr[i + 1])
        return np.asarray(S.indices[s:e]), np.asarray(S.data[s:e])

    getRow = get_row

    def get_info(self) -> dict:
        """nnz / memory summary (PETSc MatGetInfo analog)."""
        if self.host_csr is not None:
            nnz = int(self.host_csr[0][-1])
        else:
            nnz = int((self.comm.host_fetch(self.ell_vals)[: self.shape[0]] != 0).sum())
        return {
            "nnz": nnz,
            "ell_width": self.K,
            "dia_diagonals": len(self.dia_offsets),
            "rows_per_device": self.comm.local_size(self.shape[0]),
            "memory_device_bytes": int(
                self.ell_vals.size * self.ell_vals.dtype.itemsize
                + self.ell_cols.size * self.ell_cols.dtype.itemsize),
        }

    getInfo = get_info

    # ---- operator application ----------------------------------------------
    def mult_padded(self, x_padded: jax.Array) -> jax.Array:
        """SpMV on the padded global device array (jit-compiled, sharded).

        Under jit with sharded operands XLA inserts the all-gather of ``x``
        itself (GSPMD); solvers instead use the explicit shard_map path via
        :meth:`device_arrays` + ops.spmv.
        """
        if self.dia_vals is not None:
            return _jit_dia_spmv(self.dia_vals, x_padded, self.dia_offsets)
        return _jit_spmv(self.ell_cols, self.ell_vals, x_padded)

    def mult(self, x: Vec, y: Vec | None = None) -> Vec:
        ypad = self.mult_padded(x.data)
        if y is None:
            y = Vec(self.comm, self.shape[0], data=ypad, layout=self.layout)
        else:
            y.data = ypad
        return y

    def mult_transpose(self, x: Vec, y: Vec | None = None) -> Vec:
        """``y = Aᵀ x`` (PETSc MatMultTranspose) via the distributed
        transpose-SpMV program (scatter-psum, the reverse pattern of the
        all-gather forward product)."""
        prog = _mult_t_program(self)
        ypad = prog(self.device_arrays(), x.data)
        if y is None:
            return Vec(self.comm, self.shape[0], data=ypad,
                       layout=self.layout)
        y.data = ypad
        return y

    multTranspose = mult_transpose

    def diagonal(self) -> np.ndarray:
        """Host-side global diagonal (for Jacobi preconditioning)."""
        if self._diag_value is not None:
            return np.full(self.shape[0], self._diag_value)
        if self.host_csr is not None:
            return csr_diag(*self.host_csr, self.shape[0])
        cols = self.comm.host_fetch(self.ell_cols)[: self.shape[0]]
        vals = self.comm.host_fetch(self.ell_vals)[: self.shape[0]]
        gidx = np.arange(self.shape[0])[:, None]
        return np.where(cols == gidx, vals, 0.0).sum(axis=1)

    def to_scipy(self):
        import scipy.sparse as sp
        if self.host_csr is not None:
            indptr, indices, data = self.host_csr
            return sp.csr_matrix((data, indices, indptr), shape=self.shape)
        cols = self.comm.host_fetch(self.ell_cols)[: self.shape[0]]
        vals = self.comm.host_fetch(self.ell_vals)[: self.shape[0]]
        n = self.shape[0]
        rows = np.repeat(np.arange(n), cols.shape[1])
        mask = vals.ravel() != 0
        return sp.csr_matrix(
            (vals.ravel()[mask], (rows[mask], cols.ravel()[mask])),
            shape=self.shape)

    # ---- linear-operator protocol (consumed by solvers.krylov) -------------
    def device_arrays(self):
        """The raw sharded arrays consumed by shard_map solver kernels."""
        if self.dia_vals is not None:
            return (self.dia_vals,)
        return self.ell_cols, self.ell_vals

    def local_spmv(self, comm: DeviceComm):
        """Local SpMV closure for use inside shard_map.

        DIA path (banded matrices): all_gather + static shifted slices.
        ELL path (general sparsity): all_gather + gather.
        """
        from jax import lax
        axis = comm.axis
        if self.dia_vals is not None:
            offsets = self.dia_offsets
            halo = max(abs(o) for o in offsets) if offsets else 0
            lsize = comm.local_size(self.shape[0])
            ndev = comm.size

            if ndev > 1 and 0 < halo <= lsize:
                # scalable banded path: every occupied diagonal reaches at
                # most one neighbour shard, so the VecScatter is a ring
                # ppermute of `halo` boundary rows each way — O(halo) bytes
                # on the ICI instead of replicating the whole vector
                # (SURVEY.md §7.4-3: the all_gather fallback bounds scaling)
                # open chain, not a ring: shards with no incoming pair
                # (the global edges) receive zeros from ppermute itself —
                # no wrap transfer, no masking needed
                fwd = [(i, i + 1) for i in range(ndev - 1)]
                bwd = [(i, i - 1) for i in range(1, ndev)]

                def spmv(op_local, x_local):
                    (dia,) = op_local
                    acc = _accum(dia.dtype)
                    # the halo ppermutes move STORAGE-dtype rows — the
                    # halved-byte budget the low-precision layouts buy
                    left = lax.ppermute(x_local[-halo:], axis, fwd)
                    right = lax.ppermute(x_local[:halo], axis, bwd)
                    ext = jnp.concatenate([left, x_local, right])
                    y = jnp.zeros(lsize, acc or dia.dtype)
                    for d, off in enumerate(offsets):
                        seg = lax.slice_in_dim(ext, halo + int(off),
                                               halo + int(off) + lsize)
                        coeff = dia[:, d].astype(acc) if acc else dia[:, d]
                        y = y + coeff * seg
                    return y.astype(dia.dtype)

                return spmv

            def spmv(op_local, x_local):
                (dia,) = op_local
                x_full = lax.all_gather(x_local, axis, tiled=True)
                row0 = lax.axis_index(axis) * lsize
                return dia_spmv_local(dia, offsets, x_full, row0, halo)

            return spmv

        def spmv(op_local, x_local):
            cols, vals = op_local
            x_full = lax.all_gather(x_local, axis, tiled=True)
            return ell_spmv_local(cols, vals, x_full)

        return spmv

    def local_spmv_many(self, comm: DeviceComm):
        """Multi-RHS local SpMV closure: ``spmv(op_local, X_local)`` with
        ``X_local`` the device's ``(lsize, nrhs)`` block of an
        ``(n_pad, nrhs)`` row-sharded RHS block.

        The communication structure mirrors :meth:`local_spmv` exactly —
        ONE collective per apply whatever ``nrhs`` is (the whole point of
        the batched solve path): the ELL/general-DIA paths all_gather the
        entire block in one op (bytes scale with k, op count does not) and
        the banded-DIA path ships the two ``(halo, nrhs)`` boundary blocks
        over the same open-chain ppermutes.
        """
        from jax import lax
        axis = comm.axis
        if self.dia_vals is not None:
            offsets = self.dia_offsets
            halo = max(abs(o) for o in offsets) if offsets else 0
            lsize = comm.local_size(self.shape[0])
            ndev = comm.size

            if ndev > 1 and 0 < halo <= lsize:
                fwd = [(i, i + 1) for i in range(ndev - 1)]
                bwd = [(i, i - 1) for i in range(1, ndev)]

                def spmv(op_local, x_local):
                    (dia,) = op_local
                    acc = _accum(dia.dtype)
                    left = lax.ppermute(x_local[-halo:], axis, fwd)
                    right = lax.ppermute(x_local[:halo], axis, bwd)
                    ext = jnp.concatenate([left, x_local, right])
                    y = jnp.zeros((lsize, x_local.shape[1]),
                                  acc or dia.dtype)
                    for d, off in enumerate(offsets):
                        seg = lax.slice_in_dim(ext, halo + int(off),
                                               halo + int(off) + lsize)
                        coeff = (dia[:, d:d + 1].astype(acc) if acc
                                 else dia[:, d:d + 1])
                        y = y + coeff * seg
                    return y.astype(dia.dtype)

                return spmv

            def spmv(op_local, x_local):
                (dia,) = op_local
                x_full = lax.all_gather(x_local, axis, tiled=True)
                row0 = lax.axis_index(axis) * lsize
                return dia_spmv_local_many(dia, offsets, x_full, row0, halo)

            return spmv

        def spmv(op_local, x_local):
            cols, vals = op_local
            x_full = lax.all_gather(x_local, axis, tiled=True)
            return ell_spmv_local_many(cols, vals, x_full)

        return spmv

    def local_spmv_t(self, comm: DeviceComm):
        """Local transpose-SpMV closure (``y = Aᵀ x``) for shard_map bodies.

        Each device forms its rows' contribution to the full output vector
        (its rows hit columns anywhere), then one ``psum`` combines them —
        the reverse communication pattern of the all-gather forward product.
        Used by KSPLSQR (PETSc's MatMultTranspose slot).
        """
        from jax import lax
        axis = comm.axis
        if self.shape[0] != self.shape[1]:
            raise ValueError(
                "local_spmv_t supports square operators only (output is "
                f"row-partitioned like the input); shape={self.shape}")
        n = self.shape[0]
        lsize = comm.local_size(n)
        n_pad = lsize * comm.size
        if self.dia_vals is not None:
            offsets = self.dia_offsets
            halo = max(abs(o) for o in offsets) if offsets else 0
            ndev = comm.size

            def accumulate_window(dia, x_local):
                """Local rows' contributions over the ±halo column window."""
                win = jnp.zeros(lsize + 2 * halo, dia.dtype)
                for d, off in enumerate(offsets):
                    win = lax.dynamic_update_slice_in_dim(
                        win,
                        lax.dynamic_slice_in_dim(win, int(off) + halo, lsize)
                        + dia[:, d] * x_local,
                        int(off) + halo, axis=0)
                return win

            if halo == 0:
                # purely diagonal: the transpose product is entirely local
                def spmv_t(op_local, x_local):
                    (dia,) = op_local
                    return dia[:, 0] * x_local

                return spmv_t

            if halo <= lsize:
                # open-chain spill exchange: a shard's contributions reach at
                # most one neighbour each way, so ship the two halo spills
                # over ppermute instead of psum-ing an O(n) buffer (an empty
                # chain on a 1-device mesh zero-fills both spills)
                fwd = [(i, i + 1) for i in range(ndev - 1)]
                bwd = [(i, i - 1) for i in range(1, ndev)]

                def spmv_t(op_local, x_local):
                    (dia,) = op_local
                    win = accumulate_window(dia, x_local)
                    spill_l = win[:halo]           # belongs to rank i-1
                    spill_r = win[halo + lsize:]   # belongs to rank i+1
                    from_left = lax.ppermute(spill_r, axis, fwd)
                    from_right = lax.ppermute(spill_l, axis, bwd)
                    y = win[halo:halo + lsize]
                    y = y.at[:halo].add(from_left)
                    y = y.at[lsize - halo:].add(from_right)
                    return y

                return spmv_t

            def spmv_t(op_local, x_local):
                (dia,) = op_local
                row0 = lax.axis_index(axis) * lsize
                win = accumulate_window(dia, x_local)
                buf = jnp.zeros(n_pad + 2 * halo, dia.dtype)
                buf = lax.dynamic_update_slice_in_dim(buf, win, row0, axis=0)
                buf = lax.psum(buf, axis)
                y_full = lax.slice_in_dim(buf, halo, halo + n_pad)
                return lax.dynamic_slice_in_dim(y_full, row0, lsize)

            return spmv_t

        def spmv_t(op_local, x_local):
            cols, vals = op_local
            contrib = vals * x_local[:, None]
            y_full = jnp.zeros(n_pad, vals.dtype)
            y_full = y_full.at[cols.ravel()].add(contrib.ravel())
            y_full = lax.psum(y_full, axis)
            row0 = lax.axis_index(axis) * lsize
            return lax.dynamic_slice_in_dim(y_full, row0, lsize)

        return spmv_t

    def op_specs(self, axis):
        from jax.sharding import PartitionSpec as P
        if self.dia_vals is not None:
            return (P(axis, None),)
        return (P(axis, None), P(axis, None))

    def program_key(self):
        if self.dia_vals is not None:
            return ("dia", self.dia_offsets)
        return ("ell",)

    def __repr__(self):
        return (f"Mat(shape={self.shape}, K={self.K}, "
                f"devices={self.comm.size}, dtype={self.dtype})")


def coo_to_csr(shape, rows, cols, vals, mode: str = "insert"):
    """Accumulate COO triplets into a host CSR triple with PETSc's
    MatSetValues duplicate semantics.

    ``mode='insert'`` (INSERT_VALUES): the LAST write to an (i, j) slot
    wins; ``mode='add'`` (ADD_VALUES): duplicates sum. Out-of-range
    indices raise (PETSc errors on them too, absent MAT_IGNORE entries).
    Used by the facade's ``Mat.setValues`` assembly path (compat/petsc4py)
    — the ``csr=`` constructor fast path bypasses this entirely.
    """
    import scipy.sparse as sp
    nrows, ncols = int(shape[0]), int(shape[1])
    rows = np.asarray(rows, dtype=np.int64).ravel()
    cols = np.asarray(cols, dtype=np.int64).ravel()
    vals = np.asarray(vals).ravel()
    if not (rows.shape == cols.shape == vals.shape):
        raise ValueError(
            f"coo_to_csr: rows/cols/vals lengths differ "
            f"({rows.shape}, {cols.shape}, {vals.shape})")
    if len(rows) and (rows.min() < 0 or rows.max() >= nrows
                      or cols.min() < 0 or cols.max() >= ncols):
        raise ValueError(
            f"coo_to_csr: index out of range for shape {(nrows, ncols)}")
    if mode == "add":
        A = sp.coo_matrix((vals, (rows, cols)), shape=(nrows, ncols)).tocsr()
        return A.indptr, A.indices, A.data
    if mode != "insert":
        raise ValueError(f"coo_to_csr: unknown mode {mode!r}")
    # INSERT: keep the last occurrence of each (i, j). np.unique on the
    # REVERSED flat keys returns the first occurrence in reversed order —
    # i.e. the last in insertion order.
    flat = rows * np.int64(ncols) + cols
    _, first_rev = np.unique(flat[::-1], return_index=True)
    keep = len(flat) - 1 - first_rev
    A = sp.coo_matrix((vals[keep], (rows[keep], cols[keep])),
                      shape=(nrows, ncols)).tocsr()
    return A.indptr, A.indices, A.data


_MULT_T_CACHE: dict = {}


def _mult_t_program(mat: Mat):
    """Cached jitted shard_map program for the transpose product."""
    from jax.sharding import PartitionSpec as P
    comm = mat.comm
    key = (comm.mesh, mat.program_key(), mat.shape, str(mat.dtype))
    prog = _MULT_T_CACHE.get(key)
    if prog is None:
        spmv_t = mat.local_spmv_t(comm)
        axis = comm.axis
        prog = jax.jit(comm.shard_map(
            spmv_t, in_specs=(mat.op_specs(axis), P(axis)),
            out_specs=P(axis)))
        _MULT_T_CACHE[key] = prog
    return prog


@jax.jit
def _jit_spmv(cols, vals, x_padded):
    return ell_spmv_local(cols, vals, x_padded)


@functools.partial(jax.jit, static_argnums=(2,))
def _jit_dia_spmv(dia, x_padded, offsets):
    halo = max(abs(o) for o in offsets) if offsets else 0
    return dia_spmv_local(dia, offsets, x_padded, 0, halo)
