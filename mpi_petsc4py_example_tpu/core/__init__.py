from .vec import Vec
from .mat import Mat
from .shell import ShellMat
