from .vec import Vec
from .mat import Mat
