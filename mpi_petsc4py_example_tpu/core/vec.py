"""Distributed vector: a row-sharded ``jax.Array`` in HBM.

TPU-native equivalent of PETSc ``Vec`` (MPI) — reference usage:
``b.setArray(local_rhs)`` sets the local block and ``x.array`` reads it
(``test.py:30``, ``test.py:145``). Here the storage is one global array with a
``NamedSharding`` over the row axis; the user-visible (possibly uneven,
PETSc-style) ownership ranges live in a :class:`RowLayout` so local-block
views match the reference partition exactly even though the internal device
layout is uniform-padded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import DeviceComm, as_comm
from ..parallel.partition import RowLayout


class Vec:
    """Row-sharded distributed vector of logical length ``n``.

    Internally stores a zero-padded array of length ``comm.padded_size(n)``
    sharded over the mesh. All solver arithmetic happens on the raw padded
    array (``.data``); the class provides the PETSc-``Vec``-shaped views.
    """

    def __init__(self, comm, n: int, data: jax.Array | None = None,
                 dtype=jnp.float64, layout: RowLayout | None = None):
        self.comm: DeviceComm = as_comm(comm)
        self.n = int(n)
        self.layout = layout or RowLayout(self.n, self.comm.size)
        if data is None:
            n_pad = self.comm.padded_size(self.n)
            data = self.comm.put_rows(np.zeros(n_pad, dtype=dtype))
        self.data = data

    # ---- construction ------------------------------------------------------
    @classmethod
    def from_global(cls, comm, arr, dtype=None, layout=None) -> "Vec":
        comm = as_comm(comm)
        arr = np.asarray(arr)
        if dtype is not None:
            arr = arr.astype(dtype)
        v = cls(comm, arr.shape[0], data=comm.put_rows(arr), dtype=arr.dtype,
                layout=layout)
        return v

    def duplicate(self) -> "Vec":
        return Vec(self.comm, self.n, data=jnp.zeros_like(self.data),
                   layout=self.layout)

    def copy(self) -> "Vec":
        return Vec(self.comm, self.n, data=self.data, layout=self.layout)

    @property
    def dtype(self):
        return self.data.dtype

    # ---- PETSc-shaped local views ------------------------------------------
    def set_array(self, local, rank: int = 0):
        """Set this rank's local block (the reference's ``b.setArray``).

        In single-controller mode the caller usually owns the whole vector
        (``rank 0`` of a 1-rank run); pass ``rank`` to set another block.
        """
        local = np.asarray(local)
        rs, re = self.layout.range(rank)
        if local.shape[0] == self.n and rs == 0 and re == self.n:
            self.data = self.comm.put_rows(local.astype(self.data.dtype))
            return
        if local.shape[0] != re - rs:
            raise ValueError(
                f"local block for rank {rank} must have length {re - rs}, "
                f"got {local.shape[0]}")
        host = self.to_numpy()
        host[rs:re] = local
        self.data = self.comm.put_rows(host.astype(self.data.dtype))

    def set_global(self, arr):
        self.data = self.comm.put_rows(np.asarray(arr, dtype=self.data.dtype))

    def local_array(self, rank: int = 0) -> np.ndarray:
        """This rank's local block (the reference's ``x.array``)."""
        rs, re = self.layout.range(rank)
        return self.to_numpy()[rs:re]

    @property
    def array(self) -> np.ndarray:
        return self.local_array(0)

    def to_numpy(self) -> np.ndarray:
        """Gather to host, dropping padding — a counts-correct ``Gatherv``
        (multi-process meshes gather the remote shards over DCN)."""
        return self.comm.host_fetch(self.data)[: self.n].copy()

    # ---- vector arithmetic (petsc4py-Vec-shaped; solvers use raw arrays) ---
    def norm(self, norm_type: str = "2") -> float:
        """Vector norm: '2' (default, PETSc NORM_2), '1', or 'inf'.

        Padding entries are zero by construction, so device-side reductions
        over the padded array are exact for all three norms."""
        t = str(norm_type).lower()
        if t in ("2", "fro", "frobenius"):
            return float(jnp.linalg.norm(self.data))
        if t in ("1", "one"):
            return float(jnp.sum(jnp.abs(self.data)))
        if t in ("inf", "infinity"):
            return float(jnp.max(jnp.abs(self.data)))
        raise ValueError(f"unknown norm type {norm_type!r}")

    def dot(self, other: "Vec"):
        """PETSc VecDot(self, other) = otherᴴ · self — conjugates the
        SECOND argument for complex dtypes (petsc4py parity; note numpy's
        ``np.vdot(u, v)`` conjugates the first, i.e. equals ``v.dot(u)``
        here)."""
        from ..utils.dtypes import is_complex
        v = jnp.vdot(other.data, self.data)
        if is_complex(self.dtype):
            return complex(v)
        return float(v)

    def axpy(self, alpha: float, other: "Vec"):
        """self += alpha * other."""
        self.data = _axpy(jnp.asarray(alpha, self.dtype), other.data,
                          self.data)
        return self

    def aypx(self, alpha: float, other: "Vec"):
        """self = alpha * self + other."""
        self.data = _axpy(jnp.asarray(alpha, self.dtype), self.data,
                          other.data)
        return self

    def scale(self, alpha: float):
        self.data = _scale(jnp.asarray(alpha, self.dtype), self.data)
        return self

    def shift(self, alpha: float):
        """self += alpha on the logical entries (padding stays zero)."""
        host = self.to_numpy() + alpha
        self.data = self.comm.put_rows(host.astype(self.data.dtype))
        return self

    def pointwise_mult(self, a: "Vec", b: "Vec"):
        self.data = _pmult(a.data, b.data)
        return self

    def sum(self) -> float:
        return float(jnp.sum(self.data))

    def mean(self) -> float:
        return float(jnp.sum(self.data)) / self.n

    def min(self) -> tuple[int, float]:
        """(location, value) of the minimum — petsc4py's ``vec.min()``."""
        h = self.to_numpy()
        i = int(np.argmin(h))
        return i, float(h[i])

    def max(self) -> tuple[int, float]:
        """(location, value) of the maximum — petsc4py's ``vec.max()``."""
        h = self.to_numpy()
        i = int(np.argmax(h))
        return i, float(h[i])

    def waxpy(self, alpha: float, x: "Vec", y: "Vec"):
        """self = alpha*x + y (PETSc VecWAXPY)."""
        self.data = _axpy(jnp.asarray(alpha, self.dtype), x.data, y.data)
        return self

    def axpby(self, alpha: float, beta: float, x: "Vec"):
        """self = alpha*x + beta*self (PETSc VecAXPBY)."""
        self.data = _axpby(jnp.asarray(alpha, self.dtype),
                           jnp.asarray(beta, self.dtype), x.data, self.data)
        return self

    def pointwise_divide(self, a: "Vec", b: "Vec"):
        """self = a / b elementwise; 0/0 on padding stays 0."""
        self.data = _pdiv(a.data, b.data)
        return self

    def reciprocal(self):
        """self = 1/self on nonzero entries (PETSc VecReciprocal; padding
        and exact zeros stay zero, matching the Jacobi-diagonal convention)."""
        self.data = _precip(self.data)
        return self

    def normalize(self) -> float:
        """Scale to unit 2-norm; returns the prior norm."""
        nrm = self.norm()
        if nrm != 0:
            self.scale(1.0 / nrm)
        return nrm

    def set_value(self, i: int, v: float):
        """Point insert by global index (assembly-time convenience)."""
        h = self.to_numpy()
        h[i] = v
        self.set_global(h)
        return self

    setValue = set_value

    def set(self, alpha: float):
        """self[:] = alpha (PETSc VecSet)."""
        self.set_global(np.full(self.n, alpha))
        return self

    def zero(self):
        # on-device zeros: a host buffer + device_put would ship O(n) bytes
        # through the runtime per call (~2.8 s for a 537 MB vector on the
        # dev tunnel — it silently serialized into whatever consumed the
        # vector next); jnp.zeros_like dispatches a tiny cached program and
        # preserves the sharding
        self.data = jnp.zeros_like(self.data)

    def __len__(self):
        return self.n


@jax.jit
def _axpy(alpha, x, y):
    return y + alpha * x


@jax.jit
def _scale(alpha, x):
    return alpha * x


@jax.jit
def _pmult(a, b):
    return a * b


@jax.jit
def _axpby(alpha, beta, x, y):
    return alpha * x + beta * y


@jax.jit
def _pdiv(a, b):
    return jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b))


@jax.jit
def _precip(x):
    return jnp.where(x == 0, 0.0, 1.0 / jnp.where(x == 0, 1.0, x))
