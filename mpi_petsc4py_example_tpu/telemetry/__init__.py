"""Structured solve telemetry: spans, metrics registry, flight recorder,
trace export.

The observability layer PETSc deployments get from ``-log_view`` /
``PetscLogStage``, made machine-readable and per-request:

* **spans** (:mod:`.spans`) — a context-propagated hierarchical span API
  with wall/monotonic timestamps and structured attributes, emitted from
  ``KSP.solve/solve_many``, ``RefinedKSP``, ``resilient_solve`` (the
  recovery-ladder stages become child spans carrying the RecoveryEvent
  data), the ``SolveServer`` dispatcher, and the EPS/PC (MG) entries;
* **metrics registry** (:mod:`.metrics`) — typed counters/gauges/
  histograms replacing the ad-hoc ``record_*`` globals (which remain as
  thin shims in ``utils/profiling.py``), with :func:`snapshot` JSON and
  a Prometheus text exporter (``SolveServer.metrics_endpoint()``);
* **flight recorder** (:mod:`.flight`) — a bounded ring of recent span
  trees + fault/recovery events, dumped automatically on unrecovered
  errors and on demand;
* **trace export** (:mod:`.export`) — Chrome/Perfetto trace-event JSON.

Every name is registered in :mod:`.names` (``NAMES``) — validated at
runtime and by tpslint TPS014.

Gating: the METRICS registry is always on (host dict updates, the same
cost class as the globals it replaced). SPANS + flight ring + trace are
armed by :func:`enable` / the ``-telemetry`` flag; disabled they are a
shared no-op context manager — no allocation, no clock read, no device
work, zero extra XLA programs (the cfg12 bench gates the armed overhead
at <2% wall).

Runtime flags (utils/options): ``-telemetry`` (arm spans+flight),
``-telemetry_flight_len N`` (ring length), ``-telemetry_dump <path>``
(at-exit JSON dump of the metrics snapshot + flight ring).
"""

from __future__ import annotations

import atexit
import json

from .export import export_trace, trace_events
from .flight import auto_dump, recorder as flight_recorder
from .metrics import Histogram, percentile, registry
from .names import FLIGHT_FAULT_POINTS, NAMES
from .spans import (NOOP, Span, current_span, disable, enable, enabled,
                    span, start_span)

__all__ = [
    "NAMES", "FLIGHT_FAULT_POINTS", "NOOP", "Span", "Histogram",
    "auto_dump", "configure_from_options", "current_span", "disable",
    "enable", "enabled", "export_trace", "flight_recorder", "percentile",
    "prometheus_text", "registry", "reset", "snapshot", "span",
    "start_span", "trace_events",
]


def snapshot() -> dict:
    """JSON-able snapshot of every registry metric."""
    return registry.snapshot()


def prometheus_text() -> str:
    """The registry in Prometheus text exposition format."""
    return registry.prometheus_text()


def reset():
    """Clear metrics + flight ring (test isolation; spans' enabled flag
    is left as-is — use :func:`disable`)."""
    registry.reset()
    flight_recorder.clear()


_dump_armed = False


def _atexit_dump(path: str):
    payload = {"metrics": snapshot(),
               "flight": flight_recorder.entries()}
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)


def configure_from_options():
    """Apply the ``-telemetry*`` runtime flags (called from
    ``utils.options.init`` after argv parsing, and safe to call again —
    the PETSc setFromOptions idiom)."""
    global _dump_armed
    from ..utils.options import global_options
    opt = global_options()
    if opt.get_bool("telemetry", False):
        enable()
    flen = opt.get_int("telemetry_flight_len", 0)
    if flen > 0:
        flight_recorder.set_maxlen(flen)
    dump = opt.get_string("telemetry_dump")
    if dump and not _dump_armed:
        _dump_armed = True
        atexit.register(_atexit_dump, dump)
