"""The telemetry NAME REGISTRY — every span and metric the framework emits.

PETSc's ``-log_view`` works because every stage/event name is registered
up front (``PetscLogStageRegister``); a typo'd name is impossible by
construction. This module is that registry for the telemetry layer:
``NAMES`` maps every span/counter/gauge/histogram name to its kind and a
one-line description. The spans module and the metrics registry VALIDATE
against it at runtime, and tpslint rule TPS014 (telemetry-coverage)
parses this dict from the AST and flags any ``span("...")`` /
``registry.counter("...")`` call site whose name literal is missing here
— the TPS007/TPS012 registry pattern applied to observability, so a
misspelled metric cannot silently record into a parallel universe.

``FLIGHT_FAULT_POINTS`` is the declarative twin for the flight recorder:
every fault point named in ``resilience/faults.FAULT_POINTS`` must be
listed here (TPS014 checks the two ASTs against each other), recording
the contract that a fault fired at ANY point produces a flight-recorder
event (``resilience/faults.py`` routes every fired clause through
``telemetry.flight.record_fault``).

This module is stdlib-free-standing (not even stdlib imports): it is
parsed by tpslint and imported by ``resilience/faults.py``'s lazy hook,
both of which must stay framework-import-free.
"""

# name -> (kind, description); kind in {"span", "counter", "gauge",
# "histogram"}. Keep entries grouped by subsystem, alphabetical within.
NAMES = {
    # ---- spans: KSP (solvers/ksp.py) ----
    "ksp.solve": ("span", "one KSP.solve call: setup -> dispatch -> fetch "
                          "(re-entries nest as child ksp.solve spans)"),
    "ksp.solve_many": ("span", "one batched KSP.solve_many block launch"),
    "ksp.setup": ("span", "PC set_up + solve-program build/AOT-load"),
    "ksp.dispatch": ("span", "the compiled solve program's execute call"),
    "ksp.fetch": ("span", "the batched D2H result fetch"),
    "ksp.verify": ("span", "the true-residual gate decision + re-entries"),
    "ksp.autoselect": ("span", "-ksp_reduction_auto: measured-latency "
                               "reduction-plan selection at KSP.setUp "
                               "(solvers/autoselect.py)"),
    # ---- spans: PC / EPS / refinement ----
    "pc.setup": ("span", "preconditioner factor build/placement (covers "
                         "the MG/GAMG hierarchy build — the MG entry)"),
    "eps.solve": ("span", "one EPS.solve eigensolve"),
    "refine.outer": ("span", "RefinedKSP outer fp64 refinement loop"),
    "refine.step": ("span", "one outer correction step (inner solve + "
                            "fp64 residual + accumulate)"),
    # ---- spans: resilience (resilience/retry.py) ----
    "resilient.solve": ("span", "resilient_solve/_many wrapper: children "
                                "are the recovery-ladder stages"),
    "resilient.backoff": ("span", "deterministic backoff wait before a "
                                  "same-mesh retry"),
    "resilient.rebuild": ("span", "operator rebuild from the checkpoint"),
    "resilient.rollback": ("span", "DETECTED_SDC immediate re-entry from "
                                   "the verified iterate"),
    "resilient.shrink": ("span", "elastic mesh-shrink escalation (attrs: "
                                 "old/new devices, resumed_iteration)"),
    "resilient.regrow": ("span", "elastic mesh RE-GROW escalation after a "
                                 "heal (attrs: old/new devices, "
                                 "resumed_iteration)"),
    "resilient.verify": ("span", "post-recovery independent true-residual "
                                 "verification"),
    # ---- spans: serving (serving/server.py + serving/fleet.py) ----
    "serving.coalesce": ("span", "QoS-scheduling one queue snapshot into "
                                 "urgency-ordered compatible batches"),
    "serving.dispatch": ("span", "one coalesced block dispatch (root span "
                                 "on the dispatcher thread)"),
    "serving.request": ("span", "one request submit -> resolve, linked to "
                                "its batch via the batch_span attr"),
    "serving.regrow": ("span", "server-wide adoption of a re-grown mesh "
                               "after a heal (every resident session "
                               "rebuilt on the larger geometry)"),
    "serving.persistent_launch": ("span", "one persistent_serve launch: "
                                          "up to Q staged request slots "
                                          "resolved out of one resident "
                                          "multi-request program "
                                          "(serving/persistent.py)"),
    "fleet.migrate": ("span", "one session migration between replicas: "
                              "drain -> checkpoint -> re-register -> "
                              "replay"),
    "fleet.scale": ("span", "one executed autoscale decision "
                            "(grow/shrink/rebalance)"),
    # ---- spans: multi-host transport (serving/transport.py + remote.py) ----
    "rpc.call": ("span", "one client RPC call end to end: every send "
                         "attempt, backoff and idempotent retry under "
                         "one deadline (attrs: method, host, attempts)"),
    "fleet.failover": ("span", "one confirmed-host-loss re-home: every "
                               "session re-registered on a survivor "
                               "from its last shipped checkpoint "
                               "(attrs: host, sessions, "
                               "resumed_iteration)"),
    "fleet.reconcile": ("span", "one post-partition placement "
                                "reconcile: resident tables gathered, "
                                "highest-epoch/authoritative winner "
                                "kept, orphan registrations removed"),
    # ---- spans: async multisplitting (solvers/multisplit.py) ----
    "multisplit.solve": ("span", "one asynchronous two-stage multisplit "
                                 "solve: block threads + bounded-staleness "
                                 "supervisor to the consistent-cut "
                                 "convergence decision"),
    # ---- counters ----
    "dispatch.programs": ("counter", "compiled-program launches by "
                                     "program kind (ksp/ksp_many/"
                                     "megasolve/...); each launch also "
                                     "increments the 'dispatches' attr "
                                     "of the current root span — the "
                                     "megasolve one-launch gate's "
                                     "measurement"),
    "solve.count": ("counter", "solves by event label (KSPSolve(...), "
                               "EPSSolve(...), ...)"),
    "solve.iterations": ("counter", "total solver iterations"),
    "sync.count": ("counter", "host<->device sync points by kind"),
    "fault.count": ("counter", "fired fault-injection clauses by point"),
    "abft.checks": ("counter", "ABFT checksum checks performed"),
    "abft.detections": ("counter", "silent-corruption detectors fired"),
    "abft.replacements": ("counter", "in-program residual replacements"),
    "sstep.demotions": ("counter", "s-step solves demoted to classic CG "
                                   "(CA-CG basis-restart budget "
                                   "-ksp_sstep_max_replacements "
                                   "exhausted)"),
    "serving.requests": ("counter", "real requests dispatched (padding "
                                    "excluded)"),
    "serving.batches": ("counter", "coalesced block dispatches"),
    "serving.padded_cols": ("counter", "zero columns added by pow2 "
                                       "padding"),
    "serving.width": ("counter", "dispatched batches by real width "
                                 "(the width histogram)"),
    "serving.rejected": ("counter", "submissions rejected by the "
                                    "admission queue bound"),
    "serving.expired": ("counter", "requests expired by their dispatch "
                                   "deadline"),
    "serving.shed": ("counter", "bulk requests shed (resolved with the "
                                "typed overload error) to admit more "
                                "urgent traffic"),
    "qos.requests": ("counter", "admitted requests by QoS class "
                                "('default' for unlabeled)"),
    "fleet.migrations": ("counter", "executed session migrations between "
                                    "replicas"),
    "fleet.scale_decisions": ("counter", "autoscale decisions by action "
                                         "(grow/shrink/rebalance/hold)"),
    "rpc.retries": ("counter", "RPC send attempts beyond the first "
                               "(same idempotency key re-sent after a "
                               "drop/timeout) by method"),
    "rpc.duplicates": ("counter", "duplicate deliveries collapsed by the "
                                  "host-side idempotency cache (joined "
                                  "in-flight or served from the result "
                                  "cache — never re-executed)"),
    "fleet.failovers": ("counter", "confirmed host losses re-homed onto "
                                   "survivors"),
    "fleet.lease_misses": ("counter", "lease renewals that found a host "
                                      "unreachable (suspected after "
                                      "-fleet_transport_suspect_after, "
                                      "confirmed dead after "
                                      "-fleet_transport_confirm_after)"),
    "multisplit.step": ("counter", "completed async outer steps (inner "
                                   "solve + publish) by block"),
    "multisplit.resyncs": ("counter", "bounded-staleness re-syncs: a block "
                                      "waited for a partner over the "
                                      "-multisplit_max_stale bound"),
    "multisplit.block_lost": ("counter", "blocks degraded to frozen-stale "
                                         "after a device loss (each later "
                                         "re-homed by the elastic path)"),
    "elastic.mesh_shrinks": ("counter", "executed degraded-mesh rebuilds"),
    "elastic.mesh_regrows": ("counter", "executed mesh RE-GROW rebuilds "
                                        "(healed capacity re-adopted)"),
    "kernel.model_bytes": ("counter", "useful roofline-model bytes by "
                                      "kernel"),
    "kernel.seconds": ("counter", "measured device seconds by kernel"),
    "kernel.episodes": ("counter", "delta-method episodes by kernel"),
    "collective.per_iter_seconds": ("counter", "summed per-iteration wall "
                                               "by solver-loop label"),
    "collective.episodes": ("counter", "collective-latency episodes by "
                                       "label"),
    # ---- gauges ----
    "collective.reduce_sites": ("gauge", "psum/all-reduce sites per "
                                         "iteration by solver-loop label"),
    "kernel.achieved_gbps": ("gauge", "achieved effective bandwidth by "
                                      "kernel (model bytes / measured s)"),
    "solve.programs": ("gauge", "jit-compiled solver programs held "
                                "(KSP + EPS caches)"),
    "serving.queue_depth": ("gauge", "pending requests at last submit"),
    "fleet.replicas": ("gauge", "live server replicas behind the router"),
    "fleet.live_hosts": ("gauge", "transport hosts currently holding a "
                                  "fresh lease (suspected/confirmed "
                                  "hosts excluded)"),
    "autoselect.psum_latency_us": ("gauge", "measured (or probe-cached) "
                                           "per-reduce-site latency of "
                                           "the mesh, microseconds"),
    # ---- histograms (fixed buckets — metrics.py) ----
    "solve.latency_seconds": ("histogram", "end-to-end wall per solve"),
    "solve.per_iter_seconds": ("histogram", "wall per solver iteration "
                                            "(the -log_view latency row)"),
    "serving.queue_wait_seconds": ("histogram", "submit -> dispatch wait "
                                                "per request"),
    "multisplit.stale_age": ("histogram", "staleness age (versions behind "
                                          "the reader) of every boundary "
                                          "read — the -log_view staleness "
                                          "row"),
    "dispatch.requests_per_launch": ("histogram",
                                     "requests amortized into one "
                                     "persistent_serve launch — the "
                                     "-log_view requests-per-launch row "
                                     "(≫1 means the resident program is "
                                     "paying ≪1 dispatch/request)"),
    "rpc.call_seconds": ("histogram", "client RPC call wall including "
                                      "every retry and backoff under "
                                      "the call deadline — the retry "
                                      "tail is the interesting bucket "
                                      "mass"),
}

# Fault points the flight recorder records events for. MUST cover every
# key of resilience/faults.FAULT_POINTS — tpslint TPS014 parses both
# dicts and fails the lint when a fault point is missing here, so a new
# fault point cannot land without its flight-recorder event site
# (faults.Fault.error() / the silent-kind applicators route through
# telemetry.flight.record_fault for every listed point).
FLIGHT_FAULT_POINTS = (
    "ksp.solve",
    "ksp.program",
    "ksp.result",
    "eps.solve",
    "comm.put",
    "comm.fetch",
    "comm.psum",
    "spmv.result",
    "pc.apply",
    "device.lost",
    "comm.delay",
    "exchange.put",
    "rpc.send",
    "rpc.recv",
)


def name_kind(name: str) -> str:
    """The registered kind of ``name``; raises ``KeyError`` (with the
    registration hint) for unknown names — the runtime twin of TPS014."""
    try:
        return NAMES[name][0]
    except KeyError:
        raise KeyError(
            f"telemetry name {name!r} is not registered in "
            "telemetry/names.NAMES — register it (kind + description) "
            "before emitting it") from None
