"""Flight recorder — a bounded ring of recent span trees + fault events.

The post-mortem answer to "what was the solver doing when it died":
a ``collections.deque(maxlen=...)`` of the most recent completed root
span trees, fired fault-injection/runtime-fault events, and recovery-
ladder events — so a chaos-run autopsy needs NO re-execution. The ring
length is ``-telemetry_flight_len`` (default 256 entries); the ring is
only fed while telemetry is enabled (the disabled path never touches
it).

Dumps:

* :meth:`FlightRecorder.dump` — on demand, JSON to a path (default
  ``<tmpdir>/tpu_solve_flight_<pid>.json``);
* :func:`auto_dump` — called by the resilience wrappers when an error
  escapes UNRECOVERED (exhausted retries, non-retriable class, failed
  shrink) and by the serving dispatcher when a dispatch fails its
  waiting futures: the ring is written out at the moment the failure
  becomes someone else's problem.

Fault events arrive through :func:`record_fault`, which
``resilience/faults.py`` calls (lazily — this module is stdlib-only, so
the import keeps faults.py framework-free) for every fired clause at
every registered fault point; ``telemetry/names.FLIGHT_FAULT_POINTS``
declares that coverage and tpslint TPS014 enforces it against
``faults.FAULT_POINTS``. The ``fault.count`` counter increments even
when telemetry is disabled (counters are always-on, like every other
registry metric).
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time

DEFAULT_FLIGHT_LEN = 256


class FlightRecorder:
    def __init__(self, maxlen: int = DEFAULT_FLIGHT_LEN):
        self._lock = threading.Lock()
        self._entries = collections.deque(maxlen=int(maxlen))
        self.last_dump_path = None

    @property
    def maxlen(self) -> int:
        return self._entries.maxlen

    def set_maxlen(self, n: int):
        """Resize the ring, keeping the newest entries."""
        with self._lock:
            self._entries = collections.deque(self._entries,
                                              maxlen=max(1, int(n)))

    # ---- feeding ------------------------------------------------------------
    def record_span(self, tree: dict):
        with self._lock:
            self._entries.append({"type": "span", "wall": time.time(),
                                  "span": tree})

    def record_event(self, kind: str, **data):
        with self._lock:
            self._entries.append({"type": "event", "kind": str(kind),
                                  "wall": time.time(), "data": data})

    # ---- views --------------------------------------------------------------
    def entries(self) -> list:
        with self._lock:
            return list(self._entries)

    def spans(self) -> list:
        """The recorded root span trees, oldest first."""
        return [e["span"] for e in self.entries() if e["type"] == "span"]

    def events(self, kind: str | None = None) -> list:
        return [e for e in self.entries()
                if e["type"] == "event"
                and (kind is None or e["kind"] == kind)]

    def clear(self):
        with self._lock:
            self._entries.clear()
        self.last_dump_path = None

    # ---- dumping ------------------------------------------------------------
    def dump(self, path: str | None = None, reason: str = "on demand"):
        """Write the ring as JSON; returns the path written."""
        path = path or os.path.join(
            tempfile.gettempdir(), f"tpu_solve_flight_{os.getpid()}.json")
        payload = {"reason": reason, "dumped_at": time.time(),
                   "flight_len": self.maxlen, "pid": os.getpid(),
                   "entries": self.entries()}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)         # atomic, like utils/checkpoint
        self.last_dump_path = path
        return path


recorder = FlightRecorder()


def record_fault(point: str, kind: str, **data):
    """One fired fault (injected or classified-real) at a registered
    fault point. Counter always; ring entry only while telemetry is
    armed. Never raises — a telemetry failure must not mask the fault
    being recorded."""
    from .metrics import registry
    try:
        registry.counter("fault.count").inc(label=point)
        from .spans import enabled
        if enabled():
            recorder.record_event("fault", point=point, fault_kind=kind,
                                  **data)
    # tpslint: disable=TPS005 — last-resort guard: the fault path is
    # already unwinding a failure; recording it must never replace the
    # real error with a telemetry one
    except Exception:  # noqa: BLE001
        pass


def auto_dump(reason: str):
    """Dump the ring when an error escapes unrecovered (resilience
    wrappers / serving dispatcher). No-op while telemetry is disabled;
    returns the dump path or None."""
    from .spans import enabled
    if not enabled():
        return None
    try:
        return recorder.dump(reason=reason)
    except OSError:
        return None
