"""Hierarchical spans — context-propagated timing with attributes.

A :class:`Span` is one timed operation with structured attributes; spans
opened while another span is active on the SAME thread become its
children, so a solve produces a tree::

    ksp.solve {ksp_type, pc, n, devices, precision, ...}
    ├─ ksp.setup
    ├─ ksp.dispatch
    ├─ ksp.fetch
    └─ ksp.verify

Completed ROOT spans go to the flight recorder's ring buffer (and from
there to the Perfetto trace export). Per-thread stacks make the
dispatcher thread's ``serving.dispatch`` spans roots of their own trees;
cross-thread relationships (a request submitted on a client thread,
resolved on the dispatcher) use DETACHED spans (:func:`start_span`)
finished explicitly and LINKED by attribute (``batch_span``), the
Chrome-trace flow-event model without the event plumbing.

The disabled path is free by construction: :func:`span` returns a shared
no-op context manager — no allocation, no clock read, no ring append —
and no telemetry code ever touches jax (zero extra XLA programs or
device dispatches either way; ``tests/test_telemetry.py`` pins it with
the live-arrays idiom). Timestamps are dual: ``wall`` (epoch seconds,
for humans and cross-process alignment) and ``t0``/``t1``
(``perf_counter`` — monotonic, what durations and trace ``ts`` use).
"""

from __future__ import annotations

import itertools
import threading
import time

from .names import name_kind

_ENABLED = False
_ids = itertools.count(1)


class _Stacks(threading.local):
    def __init__(self):
        self.stack = []


_tls = _Stacks()


def enabled() -> bool:
    return _ENABLED


def enable(flight_len: int | None = None):
    """Arm spans + flight recorder (+ optionally resize the ring)."""
    global _ENABLED
    if flight_len is not None:
        from .flight import recorder
        recorder.set_maxlen(int(flight_len))
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False
    _tls.stack = []


class Span:
    """One timed operation. Use via :func:`span` (context manager) or
    :func:`start_span` (detached, explicit :meth:`end`)."""

    __slots__ = ("name", "span_id", "parent", "attrs", "wall", "t0", "t1",
                 "thread", "children", "_pushed")

    def __init__(self, name: str, parent=None, attrs=None):
        if name_kind(name) != "span":
            raise ValueError(f"telemetry name {name!r} is not registered "
                             "as a span")
        self.name = name
        self.span_id = next(_ids)
        self.parent = parent
        self.attrs = dict(attrs) if attrs else {}
        self.wall = time.time()
        self.t0 = time.perf_counter()
        self.t1 = None
        self.thread = threading.get_ident()
        self.children = []
        self._pushed = False

    # ---- attributes ---------------------------------------------------------
    def set_attr(self, key, value):
        self.attrs[key] = value
        return self

    def set_attrs(self, **kw):
        self.attrs.update(kw)
        return self

    # ---- context-manager protocol -------------------------------------------
    def __enter__(self):
        _tls.stack.append(self)
        self._pushed = True
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.end()
        return False

    # ---- lifecycle ----------------------------------------------------------
    def end(self):
        if self.t1 is not None:
            return self               # idempotent
        self.t1 = time.perf_counter()
        if self._pushed:
            st = _tls.stack
            if st and st[-1] is self:
                st.pop()
            elif self in st:          # unbalanced exit: drop through to it
                del st[st.index(self):]
        if self.parent is not None:
            self.parent.children.append(self)
        else:
            _finish_root(self)
        return self

    def to_dict(self) -> dict:
        return {"name": self.name, "span_id": self.span_id,
                "wall": self.wall, "t0": self.t0,
                "t1": self.t1 if self.t1 is not None else self.t0,
                "thread": self.thread,
                "attrs": {k: _jsonable(v) for k, v in self.attrs.items()},
                "children": [c.to_dict() for c in self.children]}

    def __repr__(self):
        dur = (f"{(self.t1 - self.t0) * 1e3:.2f}ms"
               if self.t1 is not None else "open")
        return f"Span({self.name}, id={self.span_id}, {dur}, {self.attrs})"


class _NoopSpan:
    """The disabled path: one shared, stateless instance."""

    __slots__ = ()
    name = ""
    span_id = 0
    children = ()
    attrs: dict = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, key, value):
        return self

    def set_attrs(self, **kw):
        return self

    def end(self):
        return self


NOOP = _NoopSpan()


def span(name: str, **attrs):
    """Open a span as a context manager; nests under the current thread's
    active span. Returns the shared no-op when telemetry is disabled."""
    if not _ENABLED:
        return NOOP
    parent = _tls.stack[-1] if _tls.stack else None
    return Span(name, parent=parent, attrs=attrs)


def start_span(name: str, **attrs):
    """A DETACHED span: no parent, not on any stack — finished by an
    explicit :meth:`Span.end`, possibly on another thread (the serving
    per-request span). No-op singleton when disabled."""
    if not _ENABLED:
        return NOOP
    return Span(name, parent=None, attrs=attrs)


def current_span():
    """The active span on this thread (None when none / disabled)."""
    st = _tls.stack
    return st[-1] if st else None


def record_program_dispatch(kind: str, count: int = 1):
    """Count one compiled-program launch (a ``prog(...)`` execute call).

    Two sinks: the always-on metrics counter ``dispatch.programs``
    (labeled by program kind — ksp / ksp_many / megasolve /
    megasolve_many), and — when spans are armed — the ``dispatches``
    attribute of THIS thread's current ROOT span, so every ``ksp.solve``
    / ``serving.dispatch`` tree reports how many launches served the
    request. That per-root attribute is the megasolve acceptance gate's
    measurement: a fused solve must report exactly 1.
    """
    from .metrics import registry
    registry.counter("dispatch.programs").inc(count, label=kind)
    if _ENABLED and _tls.stack:
        root = _tls.stack[0]
        root.attrs["dispatches"] = root.attrs.get("dispatches", 0) + count


def _finish_root(sp: Span):
    if not _ENABLED:
        # a span opened while armed may finish after disable() (e.g. a
        # detached serving.request resolved later on the dispatcher
        # thread) — drop it: the flight ring is only fed while
        # telemetry is enabled (flight.py's contract), and the cfg12
        # off-measurement must see a truly silent path
        return
    # lazy imports: flight/metrics import spans for enabled() — the
    # function-level import breaks the cycle at module-load time
    from .flight import recorder
    from .metrics import registry
    recorder.record_span(sp.to_dict())
    registry.sample()


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:                              # numpy scalars and friends
        return v.item()
    except (AttributeError, ValueError):
        return str(v)
