"""Typed metrics registry — counters, gauges, fixed-bucket histograms.

The PETSc analog is the ``-log_view`` event table, which is an OUTPUT
format over an internal registry of named stages/events; this module is
that registry made machine-readable: every instrument is created by name
against the :mod:`.names` registry (unknown names raise — the runtime
twin of tpslint TPS014), :meth:`MetricsRegistry.snapshot` returns the
whole state as a JSON-able dict, and
:meth:`MetricsRegistry.prometheus_text` renders the standard Prometheus
text exposition format (surfaced by ``SolveServer.metrics_endpoint()``).

Instruments are host-side dict/float updates under a lock — the same
cost class as the ad-hoc ``record_*`` globals they replace (zero device
work, zero extra XLA programs); ``utils/profiling.py`` keeps every
legacy ``record_*`` signature as a thin shim over this registry, and
``log_view`` is now a VIEW over it (single source of truth).

Histograms carry FIXED log-spaced buckets (stable across processes, so
fleet aggregation can sum them) plus a bounded reservoir for exact
percentile summaries: :meth:`Histogram.summary` is THE shared
percentile/stat helper — ``SolveServer.stats()`` (per-server) and
``profiling.serving_stats()`` (process-wide) both call it, so the two
views can no longer drift apart in how they compute p50/p99.
"""

from __future__ import annotations

import collections
import math
import threading
import time

from .names import NAMES, name_kind

#: fixed histogram buckets (upper bounds, seconds). Log-spaced and
#: STABLE: changing them breaks cross-process aggregation, so add — never
#: reorder — and note the change in README "Observability".
LATENCY_BUCKETS_S = (1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0,
                     3.0, 10.0, 30.0, 120.0)
PER_ITER_BUCKETS_S = (1e-7, 3e-7, 1e-6, 3e-6, 1e-5, 3e-5, 1e-4, 3e-4,
                      1e-3, 3e-3, 1e-2, 0.1)
QUEUE_WAIT_BUCKETS_S = LATENCY_BUCKETS_S
#: staleness ages are small integers (versions behind the reader), not
#: seconds — integer bucket bounds up to the largest plausible
#: -multisplit_max_stale, then +Inf for runaway staleness
STALE_AGE_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0)
#: requests riding one persistent launch are small integers bounded by
#: the slot capacity (-solve_server_max_k), not seconds
REQUESTS_PER_LAUNCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0,
                               128.0)

#: default buckets by histogram name (callers may still pass their own)
DEFAULT_BUCKETS = {
    "solve.latency_seconds": LATENCY_BUCKETS_S,
    "solve.per_iter_seconds": PER_ITER_BUCKETS_S,
    "serving.queue_wait_seconds": QUEUE_WAIT_BUCKETS_S,
    "multisplit.stale_age": STALE_AGE_BUCKETS,
    "dispatch.requests_per_launch": REQUESTS_PER_LAUNCH_BUCKETS,
}

#: bounded reservoir size per histogram — the exact-percentile window
#: (the serving layer's old 10000-wait cap, made a registry property)
RESERVOIR_LEN = 10000


class Counter:
    """Monotone float counter with one optional label dimension."""

    kind = "counter"

    def __init__(self, name: str, desc: str = ""):
        self.name = name
        self.desc = desc
        self._lock = threading.Lock()
        self._values: dict = {}     # label (or None) -> float

    def inc(self, value: float = 1.0, label=None):
        if value < 0:
            raise ValueError(f"counter {self.name}: negative increment "
                             f"{value!r} (counters are monotone)")
        with self._lock:
            self._values[label] = self._values.get(label, 0.0) + value

    def value(self, label=None) -> float:
        with self._lock:
            return float(self._values.get(label, 0.0))

    def total(self) -> float:
        with self._lock:
            return float(sum(self._values.values()))

    def items(self) -> dict:
        with self._lock:
            return dict(self._values)


class Gauge:
    """Point-in-time value with one optional label dimension."""

    kind = "gauge"

    def __init__(self, name: str, desc: str = ""):
        self.name = name
        self.desc = desc
        self._lock = threading.Lock()
        self._values: dict = {}

    def set(self, value: float, label=None):
        with self._lock:
            self._values[label] = float(value)

    def value(self, label=None) -> float:
        with self._lock:
            return float(self._values.get(label, 0.0))

    def total(self) -> float:
        """Sum over all labels — the single-number aggregate the trace
        counter tracks sample (a labeled-only gauge would otherwise
        read as its 0.0 unlabeled default)."""
        with self._lock:
            return float(sum(self._values.values()))

    def items(self) -> dict:
        with self._lock:
            return dict(self._values)


class Histogram:
    """Fixed-bucket histogram + bounded reservoir for exact percentiles.

    ``buckets`` are inclusive upper bounds; one implicit +Inf bucket
    catches overflow. :meth:`summary` computes mean/max/percentiles from
    the reservoir (exact over the last ``reservoir`` observations — the
    documented approximation window for long-running processes).
    """

    kind = "histogram"

    def __init__(self, name: str, buckets=None, desc: str = "",
                 reservoir: int = RESERVOIR_LEN):
        self.name = name
        self.desc = desc
        self.buckets = tuple(float(b) for b in
                             (buckets or DEFAULT_BUCKETS.get(
                                 name, LATENCY_BUCKETS_S)))
        if list(self.buckets) != sorted(self.buckets):
            raise ValueError(f"histogram {name}: buckets must ascend")
        self._lock = threading.Lock()
        self.counts = [0] * (len(self.buckets) + 1)   # +1: the +Inf bucket
        self.count = 0
        self.sum = 0.0
        # LIFETIME max — an early worst-case spike must survive 10k
        # later fast observations; mean is likewise lifetime sum/count,
        # only the percentiles are reservoir-windowed
        self.max = 0.0
        self._reservoir = collections.deque(maxlen=int(reservoir))

    def observe(self, value: float):
        v = float(value)
        if math.isnan(v):
            return                  # a NaN wall is a bug upstream, not data
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets):
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            self.counts[i] += 1
            self.count += 1
            self.sum += v
            self.max = max(self.max, v)
            self._reservoir.append(v)

    def reservoir(self) -> list:
        with self._lock:
            return list(self._reservoir)

    def summary(self, percentiles=(50, 99)) -> dict:
        """Shared percentile/stat computation (serving/server.py
        ``stats()`` and profiling ``serving_stats()`` both use this —
        the single code path the dedup satellite asks for). count/mean/
        max are LIFETIME; percentiles are exact over the reservoir
        window (the last ``reservoir`` observations)."""
        with self._lock:
            vals = sorted(self._reservoir)
            count, total, vmax = self.count, self.sum, self.max
        out = {"count": count,
               "mean": (total / count) if count else 0.0,
               "max": vmax}
        for q in percentiles:
            out[f"p{q}"] = percentile(vals, q)
        return out

    def bucket_counts(self) -> list:
        with self._lock:
            return list(self.counts)


def percentile(sorted_values, q: float) -> float:
    """Nearest-rank-interpolated percentile of an already-sorted list
    (numpy.percentile's default 'linear' method, without numpy — the
    registry stays importable from stdlib-only contexts)."""
    if not sorted_values:
        return 0.0
    if len(sorted_values) == 1:
        return float(sorted_values[0])
    pos = (len(sorted_values) - 1) * (float(q) / 100.0)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = pos - lo
    return float(sorted_values[lo] * (1.0 - frac)
                 + sorted_values[hi] * frac)


#: samples of counter/gauge totals taken when root spans finish — the
#: bounded time series the Perfetto counter tracks are built from
_SAMPLE_LEN = 2048


class MetricsRegistry:
    """Named instruments, validated against :mod:`.names`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}
        self._samples = collections.deque(maxlen=_SAMPLE_LEN)

    # ---- instrument accessors (create-on-first-use) -------------------------
    def _get(self, name: str, kind: str, factory):
        want = name_kind(name)      # raises on unregistered names
        if want != kind:
            raise ValueError(
                f"telemetry name {name!r} is registered as a {want}, "
                f"not a {kind}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, "counter",
                         lambda: Counter(name, NAMES[name][1]))

    def gauge(self, name: str) -> Gauge:
        return self._get(name, "gauge",
                         lambda: Gauge(name, NAMES[name][1]))

    def histogram(self, name: str, buckets=None) -> Histogram:
        return self._get(name, "histogram",
                         lambda: Histogram(name, buckets, NAMES[name][1]))

    def metrics(self) -> dict:
        with self._lock:
            return dict(self._metrics)

    def reset(self):
        with self._lock:
            self._metrics.clear()
            self._samples.clear()

    # ---- counter-track sampling (telemetry/export.py) -----------------------
    def sample(self):
        """Record one timestamped sample of every counter total and gauge
        value — called when a root span finishes, so the Perfetto counter
        tracks get one point per top-level operation (bounded deque; a
        per-increment series would be unbounded)."""
        vals = {}
        for name, m in self.metrics().items():
            if m.kind in ("counter", "gauge"):
                vals[name] = m.total()
        self._samples.append((time.perf_counter(), vals))

    def samples(self) -> list:
        with self._lock:
            return list(self._samples)

    # ---- export -------------------------------------------------------------
    def snapshot(self) -> dict:
        """The whole registry as a JSON-able dict (stable schema:
        ``{name: {type, ...}}`` — tests/test_telemetry.py pins it)."""
        out = {}
        for name, m in sorted(self.metrics().items()):
            if m.kind == "counter":
                out[name] = {
                    "type": "counter", "total": m.total(),
                    "values": {_label_key(k): v
                               for k, v in m.items().items()}}
            elif m.kind == "gauge":
                out[name] = {
                    "type": "gauge",
                    "values": {_label_key(k): v
                               for k, v in m.items().items()}}
            else:
                s = m.summary()
                out[name] = {
                    "type": "histogram", "count": s["count"],
                    "sum": m.sum, "mean": s["mean"], "p50": s["p50"],
                    "p99": s["p99"], "max": s["max"],
                    "buckets": [{"le": b, "count": c} for b, c in
                                zip(list(m.buckets) + ["+Inf"],
                                    m.bucket_counts())]}
        return out

    def prometheus_text(self) -> str:
        """Standard Prometheus text exposition format (content type
        ``text/plain; version=0.0.4``) — the ``metrics_endpoint()``
        payload."""
        lines = []
        for name, m in sorted(self.metrics().items()):
            pname = "tpu_solve_" + name.replace(".", "_")
            lines.append(f"# HELP {pname} {m.desc}")
            lines.append(f"# TYPE {pname} {m.kind}")
            if m.kind in ("counter", "gauge"):
                for label, v in sorted(m.items().items(),
                                       key=lambda kv: _label_key(kv[0])):
                    lab = ("" if label is None
                           else '{label="%s"}' % _escape(label))
                    lines.append(f"{pname}{lab} {_fmt(v)}")
            else:
                cum = 0
                for b, c in zip(m.buckets, m.bucket_counts()):
                    cum += c
                    lines.append(
                        f'{pname}_bucket{{le="{_fmt(b)}"}} {cum}')
                lines.append(f'{pname}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{pname}_sum {_fmt(m.sum)}")
                lines.append(f"{pname}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


def _label_key(label) -> str:
    return "" if label is None else str(label)


def _escape(label) -> str:
    return str(label).replace("\\", "\\\\").replace('"', '\\"')


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


#: the process-wide registry (utils/profiling shims + all span sites)
registry = MetricsRegistry()
