"""Chrome/Perfetto trace-event export of the recorded span trees.

:func:`export_trace` writes the Trace Event Format JSON
(``{"traceEvents": [...]}``) that chrome://tracing and ui.perfetto.dev
load directly:

* every span becomes a ``ph: "X"`` (complete) event on a per-thread
  track — ``ts``/``dur`` in microseconds from the process monotonic
  clock, span attributes as ``args``;
* every counter/gauge sample the registry took (one per completed root
  span — metrics.MetricsRegistry.sample) becomes a ``ph: "C"`` counter
  track point;
* ``ph: "M"`` metadata events name the process and threads.

The span source is the flight recorder's ring (the last
``-telemetry_flight_len`` root spans) — a trace is a view of recent
history, exactly like the post-mortem dump, so exporting costs nothing
during the solve itself.
"""

from __future__ import annotations

import json
import os
import threading


def trace_events() -> list:
    """The Trace Event list for the current flight ring + samples."""
    from .flight import recorder
    from .metrics import registry
    pid = os.getpid()
    events = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
               "args": {"name": "tpu-sparse-solve"}}]
    tids = {}

    def tid_of(thread_ident) -> int:
        # compact per-thread track ids (raw idents are unwieldy in the UI)
        if thread_ident not in tids:
            tids[thread_ident] = len(tids) + 1
        return tids[thread_ident]

    def emit(span: dict):
        t0, t1 = float(span["t0"]), float(span["t1"])
        events.append({
            "name": span["name"], "ph": "X", "cat": "solve",
            "ts": t0 * 1e6, "dur": max(t1 - t0, 0.0) * 1e6,
            "pid": pid, "tid": tid_of(span["thread"]),
            "args": dict(span["attrs"], span_id=span["span_id"])})
        for c in span["children"]:
            emit(c)

    for tree in recorder.spans():
        emit(tree)
    main_ident = threading.main_thread().ident
    for ident, tid in tids.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": ("main" if ident == main_ident
                              else f"thread-{ident}")}})
    for ts, vals in registry.samples():
        for name, v in vals.items():
            events.append({"name": name, "ph": "C", "ts": ts * 1e6,
                           "pid": pid, "args": {"value": v}})
    return events


def export_trace(path: str) -> dict:
    """Write (and return) the Chrome/Perfetto trace JSON for the
    recorded spans + counter samples."""
    doc = {"traceEvents": trace_events(), "displayTimeUnit": "ms",
           "otherData": {"producer": "mpi_petsc4py_example_tpu.telemetry"}}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return doc
