"""Row-block partitioning and CSR slicing, as a library.

The reference hand-rolls this idiom twice (components 6-9 of SURVEY.md §2.1:
partitioner ``test.py:67-74``/``test2.py:33-37``, CSR block slicer with indptr
rebasing ``test.py:83-117``/``test2.py:44-70``, scatter protocol, shape bcast).
Here it is provided once, with the exact same semantics:

* 1-D contiguous row-block decomposition; ``divmod`` split with the remainder
  spread over the lowest ranks.
* A sliced block is the triple ``(indptr, indices, data)`` with the indptr
  **rebased** to start at zero while column indices stay **global**.

These functions are host-side (numpy); device placement of the resulting
blocks is one ``device_put`` in :class:`..parallel.mesh.DeviceComm`.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


def row_partition(nrows: int, nparts: int) -> tuple[np.ndarray, np.ndarray]:
    """Split ``nrows`` into ``nparts`` contiguous blocks, PETSc-style.

    Returns ``(count, displ)``: block sizes and starting rows. Matches the
    reference's divmod split with the remainder given to the lowest ranks
    (``test.py:67-74``).
    """
    base, extra = divmod(nrows, nparts)
    count = np.full(nparts, base, dtype=np.int64)
    count[:extra] += 1
    displ = np.concatenate(([0], np.cumsum(count)[:-1]))
    return count, displ


def ownership_range(nrows: int, nparts: int, rank: int) -> tuple[int, int]:
    """Half-open row range ``[start, end)`` owned by ``rank``."""
    count, displ = row_partition(nrows, nparts)
    return int(displ[rank]), int(displ[rank] + count[rank])


def slice_csr_block(indptr, indices, data, rstart: int, rend: int):
    """Extract rows ``[rstart, rend)`` of a CSR matrix as a local block.

    The returned indptr is rebased to start at 0; column indices stay global
    — the contract both reference drivers establish (``test.py:84-91``,
    ``test2.py:44-49``) and that the Mat constructor consumes (§3.3).
    """
    indptr = np.asarray(indptr)
    pstart, pend = indptr[rstart], indptr[rend]
    local_indptr = indptr[rstart:rend + 1] - pstart
    return (np.ascontiguousarray(local_indptr),
            np.ascontiguousarray(np.asarray(indices)[pstart:pend]),
            np.ascontiguousarray(np.asarray(data)[pstart:pend]))


def partition_csr(indptr, indices, data, nparts: int):
    """Partition a global CSR into ``nparts`` row blocks (list of triples)."""
    nrows = len(indptr) - 1
    count, displ = row_partition(nrows, nparts)
    return [slice_csr_block(indptr, indices, data, int(displ[i]),
                            int(displ[i] + count[i]))
            for i in range(nparts)]


def concat_csr_blocks(blocks):
    """Reassemble local CSR row blocks into a global CSR triple.

    Inverse of :func:`partition_csr`; also how the Mat constructor turns the
    facade's per-rank blocks back into one host CSR before device layout.
    """
    indptrs, indices, datas = zip(*blocks)
    out_indptr = [np.asarray(indptrs[0], dtype=np.int64)]
    offset = out_indptr[0][-1]
    for p in indptrs[1:]:
        p = np.asarray(p, dtype=np.int64)
        out_indptr.append(p[1:] + offset)
        offset += p[-1]
    return (np.concatenate(out_indptr),
            np.concatenate([np.asarray(i) for i in indices]),
            np.concatenate([np.asarray(d) for d in datas]))


@dataclass(frozen=True)
class RowLayout:
    """The user-visible (possibly uneven) row ownership map of a vector/matrix.

    Kept separate from the internal uniform padded device layout; used to
    answer ``.array``-style local-block queries and to gather with the *true*
    per-shard counts (fixing the reference's equal-blocks ``Gatherv`` bug at
    ``test.py:145``, SURVEY.md §3.1).
    """
    nrows: int
    nparts: int

    @property
    def count(self) -> np.ndarray:
        return row_partition(self.nrows, self.nparts)[0]

    @property
    def displ(self) -> np.ndarray:
        return row_partition(self.nrows, self.nparts)[1]

    def range(self, rank: int) -> tuple[int, int]:
        return ownership_range(self.nrows, self.nparts, rank)
