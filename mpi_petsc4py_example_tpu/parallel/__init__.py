from .mesh import DeviceComm, get_default_comm, set_default_comm, as_comm
from .partition import (RowLayout, row_partition, ownership_range,
                        slice_csr_block, partition_csr, concat_csr_blocks)
