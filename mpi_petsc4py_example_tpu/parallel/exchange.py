"""Stale-tolerant boundary exchange for asynchronous multisplitting.

The synchronous plan zoo (classic/pipecg/s-step) stalls the whole mesh
on its slowest device every reduction. The asynchronous two-stage outer
iteration (solvers/multisplit.py) replaces those collectives with this
buffer: each block PUBLISHES its boundary iterate under a monotonically
increasing per-block version, and neighbors READ whatever version is
there — **reads never block**, and every read carries a staleness
``age`` (how many versions behind the reader the slot is). Staleness,
not synchrony, is the contract:

* :meth:`StaleExchange.publish` — version-stamp and store a block's
  iterate; keeps a bounded history ring so a *consistent cut* (all
  blocks at one matching version) stays reconstructible. The publish
  is a fault point (``exchange.put``, resilience/faults.py): ``drop``
  discards one publish (readers keep the previous version — staleness
  grows by one), ``partition`` with ``device=D:times=*`` discards every
  publish from block D while armed (a partitioned peer).
* :meth:`StaleExchange.read` — non-blocking versioned read. NEVER
  returns fresher-than-published data and never waits for it; the
  staleness age is the caller's to police (``check_staleness_bound``).
* :meth:`StaleExchange.consistent_cut` — the ONLY basis on which
  multisplit convergence may be declared (tpslint TPS018): the largest
  version every live block has actually published, with each block's
  payload *at exactly that version* from the history ring. Stale local
  norms routinely undershoot the true residual; a matching cut cannot.
* :meth:`StaleExchange.mark_lost` — a block whose device died stops
  publishing forever; its last exchanged payload is FROZEN and serves
  any read or cut from then on. This is how a mid-solve ``device.lost``
  degrades to one stale block instead of a restart: survivors keep
  iterating against the frozen boundary until the elastic re-home
  republishes it (solvers/multisplit.py).

Thread model: one writer per block id (the block's own solver thread),
any number of readers. A single lock + condition variable guards the
slots; payloads themselves are treated as immutable once published
(publishers hand over arrays and never mutate them after).

Stdlib-only (threading + resilience/faults, itself stdlib-only): the
buffer must be importable — and unit-testable — without jax.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, NamedTuple

from ..resilience import faults as _faults


class ExchangeRead(NamedTuple):
    """One non-blocking read: the payload, the version it was published
    under, and its staleness age relative to the reader (0 = the
    neighbor is at least as fresh as the reader; ``reader_version -
    version`` otherwise)."""

    payload: Any
    version: int
    age: int


class StalenessBoundExceeded(RuntimeError):
    """A convergence-path read exceeded ``-multisplit_max_stale`` and the
    caller asked for the raising check (:func:`check_staleness_bound`
    with ``strict=True``)."""


def check_staleness_bound(reads, max_stale: int, *, strict: bool = False):
    """The bounded-staleness check every convergence-feeding read must
    flow through (tpslint TPS018 recognizes this helper — and
    :meth:`StaleExchange.consistent_cut` — as the sanitizers).

    ``reads`` maps neighbor/block id -> :class:`ExchangeRead` (or is an
    iterable of ``(id, ExchangeRead)``). Returns the tuple of ids whose
    age exceeds ``max_stale`` — empty means every partner is within the
    bound and the iterate may feed a convergence decision. With
    ``strict=True`` an over-bound read raises instead, for call sites
    with no resync path.
    """
    items = reads.items() if hasattr(reads, "items") else reads
    over = tuple(sorted(nb for nb, r in items if r.age > max_stale))
    if over and strict:
        raise StalenessBoundExceeded(
            f"neighbors {list(over)} exceed the staleness bound "
            f"max_stale={max_stale} — resync before trusting this "
            "iterate")
    return over


class _Slot:
    """Per-block publication state: latest version + bounded history."""

    __slots__ = ("version", "history", "lost")

    def __init__(self, history_len: int):
        self.version = 0                       # 0 = nothing published yet
        self.history = deque(maxlen=history_len)   # (version, payload)
        self.lost = False


class StaleExchange:
    """Versioned per-block slots with non-blocking aged reads.

    ``history`` bounds how far back :meth:`consistent_cut` can look —
    it must be at least ``max_stale + 1`` for the cut to stay
    reconstructible under the staleness the supervisor tolerates
    (:class:`solvers.multisplit.MultisplitSolver` sizes it so).
    """

    def __init__(self, nblocks: int, *, history: int = 8):
        if nblocks < 1:
            raise ValueError(f"nblocks must be >= 1, got {nblocks}")
        self.nblocks = int(nblocks)
        self._slots = [_Slot(max(2, int(history)))
                       for _ in range(self.nblocks)]
        self._cv = threading.Condition()
        self.drops = 0          # publishes discarded by injected faults

    # ------------------------------------------------------------- publish
    def publish(self, block: int, payload) -> int | None:
        """Store ``payload`` as block ``block``'s next version and wake
        waiters. Returns the new version, or None when an armed
        ``exchange.put`` fault discarded the publish (the slot keeps
        serving the previous version — staleness grows by one; the
        block's OWN notion of progress still advances, which is exactly
        the async model: work is never lost, only its visibility)."""
        fault = _faults.triggered("exchange.put", device=block)
        with self._cv:
            slot = self._slots[block]
            if slot.lost:
                raise RuntimeError(
                    f"block {block} is marked lost; re-home it via "
                    "republish() instead of publish()")
            if fault is not None and fault.kind in ("drop", "partition"):
                self.drops += 1
                return None
            slot.version += 1
            slot.history.append((slot.version, payload))
            self._cv.notify_all()
            return slot.version

    def republish(self, block: int, payload, *, version: int | None = None):
        """Re-home a LOST block: install ``payload`` (canonically the
        block's last exchanged iterate, handed to the adopting survivor)
        and clear the lost mark so publishing resumes. ``version``
        defaults to the frozen slot's version — the re-homed block
        continues from where the exchange last saw it, never from
        version 0 (the provably-no-restart contract the chaos drill
        asserts)."""
        with self._cv:
            slot = self._slots[block]
            v = slot.version if version is None else int(version)
            if v < slot.version:
                raise ValueError(
                    f"re-home of block {block} at version {v} would move "
                    f"BACKWARD past the exchanged version {slot.version} "
                    "— survivors must never observe regressed state")
            slot.lost = False
            slot.version = v
            slot.history.append((v, payload))
            self._cv.notify_all()

    # --------------------------------------------------------------- reads
    def read(self, neighbor: int, reader_version: int = 0) -> ExchangeRead:
        """Non-blocking versioned read of ``neighbor``'s latest payload.
        ``reader_version`` is the reader's own version counter; the
        returned age is how many versions the slot trails it (clamped at
        0 — a fresher-than-reader neighbor is age 0). A never-published
        slot returns ``(None, 0, reader_version)``: maximally stale, so
        the bound check naturally forces an initial exchange."""
        with self._cv:
            slot = self._slots[neighbor]
            if not slot.history:
                return ExchangeRead(None, 0, max(0, int(reader_version)))
            version, payload = slot.history[-1]
            age = max(0, int(reader_version) - version)
            return ExchangeRead(payload, version, age)

    def read_all(self, reader: int, reader_version: int = 0) -> dict:
        """Every other block's latest payload, keyed by block id — the
        boundary gather of one async step. Never blocks."""
        return {nb: self.read(nb, reader_version)
                for nb in range(self.nblocks) if nb != reader}

    def latest(self, block: int) -> ExchangeRead:
        """The block's own latest published payload (age 0 by
        definition) — the re-home source after ``device.lost``."""
        return self.read(block, 0)

    def version(self, block: int) -> int:
        with self._cv:
            return self._slots[block].version

    def versions(self) -> tuple:
        """Latest published version of every block, in block order."""
        with self._cv:
            return tuple(s.version for s in self._slots)

    # ------------------------------------------------------------ liveness
    def mark_lost(self, block: int):
        """Freeze the block at its last exchanged version: no further
        publishes, reads and cuts serve the frozen payload."""
        with self._cv:
            self._slots[block].lost = True
            self._cv.notify_all()

    def lost(self) -> frozenset:
        with self._cv:
            return frozenset(i for i, s in enumerate(self._slots)
                             if s.lost)

    def wait_for(self, block: int, version: int,
                 timeout: float | None = None) -> bool:
        """Block until ``block`` has published ``version`` (or is marked
        lost, or ``timeout`` elapses). This is the RESYNC path — the one
        deliberate wait in the async tier, taken only when the
        bounded-staleness supervisor finds a partner over the bound.
        Returns True when the version (or the lost mark — waiting
        further is futile) arrived."""
        deadline = (None if timeout is None
                    else threading.TIMEOUT_MAX if timeout < 0
                    else timeout)
        with self._cv:
            def ready():
                s = self._slots[block]
                return s.version >= version or s.lost
            return self._cv.wait_for(ready, timeout=deadline)

    def wait_change(self, timeout: float | None = None):
        """Park until someone publishes/marks/kicks (or ``timeout``
        elapses) — the supervisor's poll gate. Spurious wakeups are
        fine: callers re-derive state from :meth:`consistent_cut`."""
        with self._cv:
            self._cv.wait(timeout=timeout)

    def kick(self):
        """Wake every waiter without changing state (a worker exiting
        tells the supervisor to take a final look)."""
        with self._cv:
            self._cv.notify_all()

    # ----------------------------------------------------- consistent cut
    def consistent_cut(self):
        """The matching-version cut convergence may be declared on.

        Returns ``(cut_version, payloads)`` where ``cut_version`` is the
        largest version every LIVE block has published and ``payloads``
        maps every block id to its payload *at that exact version* —
        lost blocks contribute their frozen latest instead (their
        staleness is the accepted degradation cost). Returns None when
        no such cut exists: nothing published yet, or some block's
        history ring no longer holds the cut version (the supervisor
        then waits for the next publish rather than declaring
        convergence on mismatched iterates — stale local norms are
        NEVER a convergence basis; tpslint TPS018 enforces the
        call-site half of that contract)."""
        with self._cv:
            live = [(i, s) for i, s in enumerate(self._slots) if not s.lost]
            if not live:
                return None
            cut = min(s.version for _, s in live)
            if cut < 1:
                return None
            payloads = {}
            for i, slot in enumerate(self._slots):
                if slot.lost:
                    if not slot.history:
                        return None
                    payloads[i] = slot.history[-1][1]
                    continue
                for version, payload in slot.history:
                    if version == cut:
                        payloads[i] = payload
                        break
                else:
                    return None        # ring pruned past the cut
            return cut, payloads

    def __repr__(self):
        with self._cv:
            vs = tuple(s.version for s in self._slots)
            lost = tuple(i for i, s in enumerate(self._slots) if s.lost)
        return (f"StaleExchange(nblocks={self.nblocks}, versions={vs}, "
                f"lost={lost or '()'}, drops={self.drops})")
