"""Device-mesh communication substrate — the framework's MPI replacement.

The reference distributes work over an ``mpi4py`` communicator (OpenMPI;
reference ``test.py:55-57``, ``environment.yaml:4``).  Here the communicator is
a 1-D :class:`jax.sharding.Mesh` over TPU chips: data placement happens through
``NamedSharding`` (XLA moves bytes over PCIe/ICI/DCN), and solver-internal
collectives (the reference's library-internal ``MPI_Allreduce`` for dots and
``VecScatter`` halo exchanges) become ``lax.psum`` / ``lax.all_gather`` /
``lax.ppermute`` inside ``shard_map``-decorated, jit-compiled programs.

No rank-conditional code: every helper is SPMD. A 1-device mesh degenerates
cleanly (collectives become no-ops under XLA).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..resilience import faults as _faults

# jax.shard_map stabilized at top level (with ``check_vma``) in newer jax;
# older versions only ship jax.experimental.shard_map.shard_map (with
# ``check_rep``). Resolve once at import so solver program construction is
# version-agnostic.
jax_shard_map_stable = getattr(jax, "shard_map", None)
if jax_shard_map_stable is not None:
    _SHARD_MAP = jax_shard_map_stable
else:
    from jax.experimental.shard_map import shard_map as _SHARD_MAP

ROW_AXIS = "rows"

# ``device_put(..., may_alias=False)`` where available (jax >= 0.4.31):
# placements must hand back XLA-OWNED buffers — see DeviceComm._put.
# Older jax has no kwarg AND no zero-copy CPU fast path, so plain
# device_put already copies there.
try:
    import inspect as _inspect
    _NO_ALIAS = ({"may_alias": False}
                 if "may_alias" in _inspect.signature(jax.device_put).parameters
                 else {})
except (ValueError, TypeError):   # signature introspection unavailable
    _NO_ALIAS = {}

# Registry of arrays produced by host->device PLACEMENT (device_put).
# On the jax 0.4.x CPU runtime, DONATING a placement-sourced buffer is
# unsafe: the in-place output keeps pointing at memory the runtime
# reclaims anyway, and the next same-size placement lands in it — the
# solve "output" then silently re-reads as its own initial guess (or
# garbage/Inf once the block is recycled further). Program-OUTPUT
# buffers donate correctly, so the solve entry points re-own (copy) an
# initial guess iff it came straight from placement (`is_placed`) —
# the serving hot path, whose donated guesses are prior program
# outputs, keeps its zero-allocation repeat dispatch.
_PLACED: dict = {}


def _mark_placed(arr):
    import weakref
    k = id(arr)
    try:
        _PLACED[k] = weakref.ref(arr, lambda _r, _k=k: _PLACED.pop(_k, None))
    except TypeError:        # non-weakref-able (tracers in tests): skip
        pass
    return arr


def is_placed(arr) -> bool:
    """True iff ``arr`` is an array object returned by a DeviceComm
    placement call (``_put``/``put_rows``/``put_rows_many``) — the
    donation-unsafe provenance (see ``_PLACED``)."""
    r = _PLACED.get(id(arr))
    return r is not None and r() is arr


def faulted_psum(x, axis: str):
    """``lax.psum`` with the ``comm.psum`` fault point applied at TRACE
    time (resilience/faults.py): 'drop' elides the reduction — every shard
    keeps its local partial, a lost allreduce — and 'corrupt' poisons the
    reduced value (NaN for inexact dtypes, bit-flip for integers). With no
    fault plan armed this IS ``lax.psum``; programs traced while a psum
    fault could fire are cache-isolated via ``faults.trace_key()`` in the
    solver program cache key (solvers/krylov.py). The one injectable-psum
    implementation — DeviceComm.psum and the solver-loop reductions both
    route through it.
    """
    fault = _faults.triggered("comm.psum")
    if fault is None:
        return lax.psum(x, axis)
    if fault.kind == "drop":
        return x
    y = lax.psum(x, axis)
    if jnp.issubdtype(jnp.result_type(y), jnp.inexact):
        return y * jnp.asarray(jnp.nan, jnp.result_type(y))
    return ~y


class DeviceComm:
    """A communicator-shaped object wrapping a 1-D device mesh.

    Plays the role the ``comm`` argument plays in the reference wrapper API
    (``petsc_funcs.py:5,13`` take ``comm`` first) — the facade keeps that
    argument slot, now carrying a mesh instead of an MPI communicator.
    """

    def __init__(self, mesh: Mesh | None = None, axis: str = ROW_AXIS,
                 devices=None, n_devices: int | None = None):
        if mesh is None:
            if devices is None:
                from ..utils.phases import stamp
                stamp("tunnel_init_begin")   # first jax.devices() initializes
                devices = jax.devices()      # the backend (tunnel on axon)
                stamp("tunnel_init_end")
                if n_devices is not None:
                    devices = devices[:n_devices]
            mesh = Mesh(np.asarray(devices), (axis,))
        self.mesh = mesh
        self.axis = axis
        # device ids of the mesh members, precomputed for the hot-path
        # lost-device guards (resilience/faults.check_lost / mesh_fault)
        self.device_ids = tuple(int(d.id) for d in self.mesh.devices.ravel())

    # ---- MPI-communicator-shaped info --------------------------------------
    @property
    def size(self) -> int:
        """Number of shards — the analog of ``comm.Get_size()``."""
        return self.mesh.shape[self.axis]

    @property
    def devices(self):
        return list(self.mesh.devices.ravel())

    @property
    def platform(self) -> str:
        """Platform of the mesh's devices ('cpu'/'tpu') — kernel fast-path
        gates key on THIS, not the process default backend: a CPU-device
        mesh in a TPU-capable process must take the CPU paths (ADVICE r4)."""
        return self.mesh.devices.ravel()[0].platform

    def __repr__(self):
        return f"DeviceComm(size={self.size}, axis={self.axis!r})"

    def fingerprint(self) -> dict:
        """Plain-data mesh descriptor for cross-host exchange (the
        transport hello/stats payload — serving/remote.py): platform,
        shard count and member device ids. Deliberately carries NO
        device handles, so it pickles across processes; the elastic
        checkpoint format never encodes a mesh size, and this is how a
        peer still learns (and reports) what geometry is serving."""
        return {"platform": self.platform, "size": int(self.size),
                "device_ids": list(self.device_ids)}

    # ---- shardings ---------------------------------------------------------
    @property
    def row_sharding(self) -> NamedSharding:
        """Shard the leading axis across the mesh (1-D row-block layout)."""
        return NamedSharding(self.mesh, P(self.axis))

    @property
    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def spec(self, *axes) -> P:
        return P(*axes)

    # ---- padded row-block layout -------------------------------------------
    # Internal layout is uniform: every device owns exactly ``local_size(n)``
    # rows, the global arrays padded with zeros to ``padded_size(n)``. User
    # visible (possibly uneven, PETSc-style) ownership ranges are maintained
    # by the callers (see parallel.partition / the facade).
    def local_size(self, n: int) -> int:
        return -(-n // self.size)

    def padded_size(self, n: int) -> int:
        return self.local_size(n) * self.size

    def pad_rows(self, arr: np.ndarray, n: int | None = None) -> np.ndarray:
        """Zero-pad the leading axis of a host array to ``padded_size``."""
        n = arr.shape[0] if n is None else n
        n_pad = self.padded_size(n)
        if arr.shape[0] == n_pad:
            return arr
        pad = [(0, n_pad - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
        return np.pad(arr, pad)

    @property
    def multiprocess(self) -> bool:
        """True when the mesh spans several controller processes (DCN mode:
        ``jax.distributed.initialize`` ran and devices belong to more than
        one host — the reference's multi-node ``mpirun`` analog)."""
        return jax.process_count() > 1

    def _put(self, arr, sharding) -> jax.Array:
        """SPMD data placement: every process holds the same host array (the
        reference's replicated-driver model); single-process uses one
        ``device_put``, multi-process builds the global array from the
        per-process addressable pieces."""
        _faults.check("comm.put")     # injectable placement failure
        _faults.check_lost(self.device_ids)   # mesh holds a LOST device?
        if not self.multiprocess:
            # may_alias=False: CPU device_put is otherwise ZERO-COPY — the
            # device array aliases the caller's numpy memory (sharded
            # placement aliases interior SLICES), so mutating the source
            # array after placement would silently change device data.
            # Owned copies match TPU put semantics (host->HBM always
            # copies). NOTE this does NOT make the result donation-safe
            # on the CPU runtime — see _PLACED/is_placed above.
            return _mark_placed(jax.device_put(arr, sharding, **_NO_ALIAS))
        return _mark_placed(jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]))

    def put_rows(self, arr, dtype=None) -> jax.Array:
        """Host array -> device array sharded on the leading (row) axis.

        This is the TPU-native replacement for the reference's hand-written
        scatter protocol (pickled lengths + 4 buffered ``Send``s,
        ``test.py:101-106``): one ``device_put`` with a ``NamedSharding`` and
        the runtime moves each block to its device (over PCIe/ICI; across
        hosts each process places only its addressable shards).
        """
        arr = np.asarray(arr, dtype=dtype)
        arr = self.pad_rows(arr)
        return self._put(arr, self.row_sharding)

    def put_rows_many(self, arrs) -> list:
        """Batch variant of :meth:`put_rows`: ONE placement call for
        several (already dtype-final) row-sharded arrays.

        Sequential per-array ``device_put``s pay the runtime's fixed
        dispatch cost once EACH — on the remote dev tunnel that is a
        ~0.1 s+ round trip per array, which is where cfg4's unitemized
        assembly wall went (round-6 VERDICT weak #1: three placements —
        ELL cols, ELL vals, DIA vals — for a 65k-row matrix). A single
        ``jax.device_put`` over the list lets the runtime pipeline one
        transfer.
        """
        host = [self.pad_rows(np.asarray(a)) for a in arrs]
        if not self.multiprocess:
            # one placement call -> ONE 'comm.put' fault check (the
            # multiprocess path checks inside _put per array — no extra
            # check here, or injected schedules would double-count)
            _faults.check("comm.put")
            _faults.check_lost(self.device_ids)
            # owned buffers, same reason as _put
            return [_mark_placed(a)
                    for a in jax.device_put(host, self.row_sharding,
                                            **_NO_ALIAS)]
        return [self._put(a, self.row_sharding) for a in host]

    def put_axis0(self, arr, dtype=None) -> jax.Array:
        """Axis-0 sharding WITHOUT row padding (pre-shaped block stacks)."""
        return self._put(np.asarray(arr, dtype=dtype), self.row_sharding)

    def put_replicated(self, arr, dtype=None) -> jax.Array:
        """Host array -> replicated device array (the analog of ``bcast``)."""
        return self._put(np.asarray(arr, dtype=dtype),
                         self.replicated_sharding)

    def put_spec(self, arr, spec: P, dtype=None) -> jax.Array:
        """Host array -> device array with an arbitrary PartitionSpec."""
        return self._put(np.asarray(arr, dtype=dtype),
                         NamedSharding(self.mesh, spec))

    def host_fetch(self, x) -> np.ndarray:
        """Device array -> full host copy on EVERY process (the
        counts-correct ``Gatherv``+``bcast``). Single-process is one D2H
        copy; multi-process gathers the remote shards over DCN."""
        if not self.multiprocess or getattr(x, "is_fully_addressable", True):
            out = np.asarray(x)
        else:
            from jax.experimental import multihost_utils
            out = np.asarray(multihost_utils.process_allgather(x, tiled=True))
        fault = _faults.triggered("comm.fetch")
        if fault is not None:
            if fault.kind == "unavailable":
                raise fault.error()
            out = out.copy()
            if fault.kind == "drop":      # a lost gather contribution
                out[...] = 0
            elif out.size:                # 'corrupt': poison one element
                flat = out.reshape(-1)
                flat[0] = (np.nan if np.issubdtype(out.dtype, np.inexact)
                           else ~flat[0])
        return out

    # ---- collective helpers (usable INSIDE shard_map) ----------------------
    def psum(self, x):
        """Sum across the mesh — the analog of ``MPI_Allreduce(SUM)``.
        Injectable via the ``comm.psum`` fault point (:func:`faulted_psum`).
        """
        return faulted_psum(x, self.axis)

    def pmax(self, x):
        return lax.pmax(x, self.axis)

    def all_gather(self, x, axis: int = 0):
        """Concatenate shards — the general VecScatter replacement."""
        return lax.all_gather(x, self.axis, axis=axis, tiled=True)

    def shift(self, x, step: int = 1):
        """Ring ``ppermute`` — neighbor/halo exchange for stencil SpMV."""
        n = self.size
        perm = [(i, (i + step) % n) for i in range(n)]
        return lax.ppermute(x, self.axis, perm=perm)

    def device_index(self):
        """This shard's index — the in-SPMD analog of ``comm.Get_rank()``."""
        return lax.axis_index(self.axis)

    # ---- SPMD program construction -----------------------------------------
    def shard_map(self, fn, in_specs, out_specs, check_vma: bool = False):
        """Wrap ``fn`` (written over *local* shards) as an SPMD program."""
        if _SHARD_MAP is jax_shard_map_stable:
            return _SHARD_MAP(fn, mesh=self.mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_vma)
        # pre-0.6 jax: the experimental entry point spells the replication
        # check ``check_rep``
        return _SHARD_MAP(fn, mesh=self.mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


def full_vector_local_apply(fn, comm: DeviceComm, n: int):
    """Lift ``y = fn(x)`` on the full global vector to a shard-local apply.

    Returns ``apply(x_local) -> y_local`` for use inside shard_map bodies:
    all-gathers the sharded vector, applies ``fn`` replicated per device on
    the unpadded length-``n`` view, and hands back this device's row block.
    Shared by shell operators (core.shell.ShellMat) and PCSHELL.
    """
    axis = comm.axis
    lsize = comm.local_size(n)
    n_pad = lsize * comm.size

    def apply(x_local):
        x_full = lax.all_gather(x_local, axis, tiled=True)
        y = fn(x_full[:n] if n_pad != n else x_full)
        ypad = jnp.pad(y, (0, n_pad - n)) if n_pad != n else y
        i = lax.axis_index(axis)
        return lax.dynamic_slice_in_dim(ypad, i * lsize, lsize)

    return apply


def init_multihost(coordinator_address: str, num_processes: int,
                   process_id: int, **kw) -> DeviceComm:
    """Join a multi-controller job and return the global communicator.

    The DCN analog of launching under ``mpirun -n N`` across nodes
    (reference L1, SURVEY.md §5.8): every controller process calls this with
    the same coordinator address; afterwards ``jax.devices()`` spans all
    hosts and the returned :class:`DeviceComm` is the global 1-D mesh.
    Collectives inside compiled solver programs ride ICI within a host/pod
    and DCN across — placement is unchanged framework code either way.
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kw)
    comm = DeviceComm()
    set_default_comm(comm)
    return comm


_default_comm: DeviceComm | None = None


def get_default_comm() -> DeviceComm:
    """Process-wide default communicator (all visible devices, 1-D mesh)."""
    global _default_comm
    if _default_comm is None:
        _default_comm = DeviceComm()
    return _default_comm


def set_default_comm(comm: DeviceComm | None):
    global _default_comm
    _default_comm = comm


def as_comm(comm) -> DeviceComm:
    """Coerce ``None`` / a Mesh / a DeviceComm into a DeviceComm."""
    if comm is None:
        return get_default_comm()
    if isinstance(comm, DeviceComm):
        return comm
    if isinstance(comm, Mesh):
        return DeviceComm(mesh=comm, axis=comm.axis_names[0])
    # Facade communicator objects (compat.mpi4py) carry a DeviceComm.
    dc = getattr(comm, "device_comm", None)
    if dc is not None:
        return dc if isinstance(dc, DeviceComm) else as_comm(dc)
    raise TypeError(f"cannot interpret {comm!r} as a DeviceComm")
