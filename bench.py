#!/usr/bin/env python
"""Benchmark harness — creates the baseline BASELINE.md says doesn't exist.

Headline metric (BASELINE.json): KSP iterations/second and time-to-rtol=1e-6
for CG on the 3D 7-point Poisson operator, with residual parity vs a CPU
oracle. The TPU path runs the matrix-free stencil operator (fp32, Jacobi-CG,
one jit-compiled program, fused Pallas stencil+dot kernel); the baseline is
scipy.sparse.linalg.cg (fp64 CPU) on the identical problem and tolerance —
the stand-in for 8-rank PETSc KSPCG (petsc4py is not installable here; scipy
is the only CPU oracle, SURVEY.md §4).

Measurement methodology (two numbers, both reported):

- **end-to-end wall**: median ± spread over ``--reps`` timed solves. On the
  dev runtime every program call pays a fixed ~0.1-1 s tunnel round trip
  (execute + result fetch) that no kernel can amortize; production TPU
  runtimes pay microseconds. The e2e wall therefore *includes* that latency
  and is the conservative number used for ``vs_baseline``.
- **on-chip iteration rate**: the latency-free rate, measured by the delta
  method — two fixed-iteration solves (norm type 'none') whose wall
  difference isolates pure loop time: ``per_iter = (w_hi - w_lo)/(it_hi -
  it_lo)``, median over ``--reps``. From it the achieved HBM traffic
  (11 vector passes/iteration on the fused CG path) and the fraction of the
  ~819 GB/s v5e roof are derived — the "bandwidth-bound" claim is measured,
  not asserted.

Prints ONE JSON line:
  {"metric": ..., "value": on_chip_iters_per_sec, "unit": "iters/s",
   "vs_baseline": cpu_wall / tpu_e2e_wall, "extra": {...}}

Usage: python bench.py [--quick] [--n NX] [--rtol R] [--reps K]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

import numpy as np

# importing the package first applies TPU_SOLVE_PLATFORM / x64 config before
# any jax backend initialization (needed for forced-CPU smoke runs)
import mpi_petsc4py_example_tpu  # noqa: F401

HBM_ROOF_GBPS = 819.0   # v5e HBM bandwidth (How-to-Scale-Your-Model tables)
# fused CG+Jacobi step traffic (krylov.cg_stencil_kernel): Adot reads p /
# writes Ap (2), the x/r update fusion reads x,p,r,Ap and writes x,r (6),
# the p-update reads r,p and writes p (3) -> 11 vector passes per iteration
PASSES_PER_ITER = 11


def make_problem(nx, pc_type="jacobi"):
    import jax.numpy as jnp

    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import StencilPoisson3D

    comm = tps.DeviceComm()
    op = StencilPoisson3D(comm, nx, dtype=jnp.float32)
    n = nx ** 3
    rng = np.random.default_rng(7)
    x_true = rng.random(n).astype(np.float32)
    b = np.asarray(op.mult(tps.Vec.from_global(comm, x_true)).to_numpy())

    ksp = tps.KSP().create(comm)
    ksp.set_operators(op)
    ksp.set_type("cg")
    ksp.get_pc().set_type(pc_type)
    return comm, op, ksp, b


def tpu_solve(nx, rtol, pc_type="jacobi", reps=3):
    """Converged CG; returns (iters, e2e walls list, x, b)."""
    comm, op, ksp, b = make_problem(nx, pc_type)
    ksp.set_tolerances(rtol=rtol, atol=0.0, max_it=20000)
    x, bv = op.get_vecs()
    bv.set_global(b)
    ksp.solve(bv, x)          # warm-up: compiles the program
    walls = []
    for _ in range(reps):
        x.zero()
        t0 = time.perf_counter()
        res = ksp.solve(bv, x)
        walls.append(time.perf_counter() - t0)
    return res.iterations, walls, x.to_numpy(), b, res


def _fixed_iter_solver(nx, max_it):
    comm, op, ksp, b = make_problem(nx, "jacobi")
    ksp.set_norm_type("none")
    ksp.set_tolerances(rtol=0.0, atol=0.0, max_it=max_it)
    x, bv = op.get_vecs()
    bv.set_global(b)
    ksp.solve(bv, x)          # warm-up
    return ksp, x, bv


def _delta_protocol(make_solver, run_one, reps, lo, hi, autoscale):
    """The ONE delta-method measurement protocol (single- and multi-RHS
    callers share it): two fixed-iteration solves whose wall difference
    isolates pure loop time, with the iteration delta auto-scaled so the
    measured loop time sits well above the run-to-run launch-latency
    noise (~tens of ms) — a pilot delta estimates the rate, then ``hi``
    is re-chosen for ~0.75 s of loop work, backing off under early
    recurrence blow-up. ``run_one(solver) -> (wall_s, iterations)`` is
    the only thing that differs between callers.
    """
    solvers = {m: make_solver(m) for m in (lo, hi)}

    def one_delta(a, b_):
        ws, its = {}, {}
        for max_it in (a, b_):
            # actual iterations, not max_it: a tol=0 fp32 run eventually
            # overflows its recurrence to inf and exits early — dividing
            # by the requested count would fake an arbitrarily fast rate
            ws[max_it], its[max_it] = run_one(solvers[max_it])
        return (ws[b_] - ws[a]) / max(its[b_] - its[a], 1), its[b_]

    pilot, _ = one_delta(lo, hi)
    target = int(0.75 / max(pilot, 1e-7))
    if autoscale and target > 2 * (hi - lo):  # delta too small for the noise
        hi2 = lo + min(target, 200000)
        solvers[hi2] = make_solver(hi2)
        _, actual = one_delta(lo, hi2)
        if actual < hi2:              # recurrence blow-up: stay under it
            hi2 = max(int(actual * 0.9), hi)
            if hi2 not in solvers:
                solvers[hi2] = make_solver(hi2)
            # the delta stayed shorter than intended — compensate with
            # extra samples beyond the user's --reps
            reps = max(reps, 5)
        hi = hi2
    return [one_delta(lo, hi)[0] for _ in range(reps)]


def delta_rate(make_solver, reps=3, lo=20, hi=520, autoscale=True):
    """Delta-method on-chip per-iteration time (see module docstring);
    returns a per_iter_seconds list.

    ``make_solver(max_it) -> (ksp, x, bv)`` builds a warmed fixed-iteration
    solver (norm type 'none'). The one measurement protocol shared by
    bench.py and benchmarks/run_all.py (configs 5 and 7) lives in
    :func:`_delta_protocol`.
    """
    def run_one(solver):
        ksp, x, bv = solver
        x.zero()
        t0 = time.perf_counter()
        r = ksp.solve(bv, x)
        return time.perf_counter() - t0, r.iterations

    return _delta_protocol(make_solver, run_one, reps, lo, hi, autoscale)


def on_chip_rate(nx, reps=3, lo=20, hi=520):
    """Delta-method per-iteration time for CG+Jacobi at nx^3."""
    return delta_rate(lambda m: _fixed_iter_solver(nx, m),
                      reps=reps, lo=lo, hi=hi)


def delta_rate_many(make_solver, B, reps=3, lo=20, hi=220,
                    autoscale=True):
    """Delta-method per-iteration time for a BATCHED fixed-iteration
    solver: the :func:`_delta_protocol` discipline over
    ``KSP.solve_many`` launches (one iteration advances ALL k columns;
    a launch's iteration count is its slowest column's). Shared by
    bench.py and benchmarks/run_all.py (config 7).

    ``make_solver(max_it) -> ksp`` builds a warmed fixed-iteration
    (norm 'none') solver.
    """
    def run_one(kf):
        t0 = time.perf_counter()
        r = kf.solve_many(B.copy())
        return time.perf_counter() - t0, max(r.iterations)

    return _delta_protocol(make_solver, run_one, reps, lo, hi, autoscale)


def batched_delta(nx, k=8, reps=3, lo=20, hi=220):
    """Delta-method per-iteration time of the BATCHED (k-RHS) stencil CG
    kernel (the multi-RHS Pallas pipeline + one-psum-per-phase fused
    reductions) on the headline problem."""
    comm, op, ksp, b = make_problem(nx, "jacobi")
    n = nx ** 3
    rng = np.random.default_rng(11)
    B = np.stack([b] + [np.asarray(
        op.mult(mpi_petsc4py_example_tpu.Vec.from_global(
            comm, rng.random(n).astype(np.float32))).to_numpy())
        for _ in range(k - 1)], axis=1)

    def fixed(max_it):
        kf = mpi_petsc4py_example_tpu.KSP().create(comm)
        kf.set_operators(op)
        kf.set_type("cg")
        kf.get_pc().set_type("jacobi")
        kf.set_norm_type("none")
        kf.set_tolerances(rtol=0.0, atol=0.0, max_it=max_it)
        kf.solve_many(B.copy())            # warm-up / compile
        return kf

    return delta_rate_many(fixed, B, reps=reps, lo=lo, hi=hi)


def serving_episode(nx, requests=64, max_k=16, window=0.003, rtol=1e-6):
    """Coalesced-serving episode (--serving): the SAME request set
    through a SolveServer session (block-CG dispatch, donated buffers)
    and through sequential per-request ``ksp.solve`` launches, on the
    headline stencil operator. Prints one extra JSON line; the ratio
    measures dispatch amortization + block-kernel throughput (cfg9 in
    benchmarks/run_all.py is the full Poisson-arrival protocol with the
    injected-fault recovery)."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.serving import SolveServer

    comm, op, ksp, b = make_problem(nx, "jacobi")
    n = nx ** 3
    rng = np.random.default_rng(13)
    B = np.stack([np.asarray(op.mult(tps.Vec.from_global(
        comm, rng.random(n).astype(np.float32))).to_numpy())
        for _ in range(requests)], axis=1)
    ksp.set_tolerances(rtol=rtol, atol=0.0, max_it=20000)
    x, bv = op.get_vecs()
    bv.set_global(B[:, 0])
    ksp.solve(bv, x)                  # warm the k=1 program
    t0 = time.perf_counter()
    for j in range(requests):
        x, bv = op.get_vecs()
        bv.set_global(B[:, j])
        ksp.solve(bv, x)
    seq_wall = time.perf_counter() - t0

    srv = SolveServer(comm, window=window, max_k=max_k)
    srv.register_operator("stencil", op, pc_type="jacobi", rtol=rtol,
                          warm_widths=(max_k,))
    t0 = time.perf_counter()
    futs = [srv.submit("stencil", B[:, j]) for j in range(requests)]
    res = [f.result(600) for f in futs]
    wall = time.perf_counter() - t0
    stats = srv.stats()
    srv.shutdown()
    assert all(r.converged for r in res)
    line = {
        "metric": f"serving: {requests} coalesced solves ({nx}^3 "
                  f"stencil, max_k={max_k}) vs sequential dispatch",
        "value": round(requests / wall, 2) if wall > 0 else 0.0,
        "unit": "solves/s",
        "vs_baseline": round(seq_wall / wall, 3) if wall > 0 else 0.0,
        "extra": {
            "seq_solves_per_s": round(requests / seq_wall, 2)
            if seq_wall > 0 else 0.0,
            "mean_batch_width": round(stats["mean_width"], 2),
            "batches": stats["batches"],
            "queue_wait_p50_ms": round(
                stats.get("queue_wait_p50_s", 0.0) * 1e3, 2),
        },
    }
    print(json.dumps(line))


def cpu_baseline(nx, b: np.ndarray, rtol: float):
    """scipy fp64 CG on the identical operator/tolerance."""
    import scipy.sparse.linalg as spla

    from mpi_petsc4py_example_tpu.models import poisson3d_csr

    A = poisson3d_csr(nx).astype(np.float64)
    bb = b.astype(np.float64)
    iters = [0]

    def cb(_):
        iters[0] += 1

    # Jacobi preconditioning to match the TPU configuration (diag = 6)
    M = spla.LinearOperator(A.shape, matvec=lambda v: v / 6.0)
    t0 = time.perf_counter()
    x, info = spla.cg(A, bb, rtol=rtol, atol=0.0, maxiter=20000,
                      M=M, callback=cb)
    wall = time.perf_counter() - t0
    return iters[0], wall, x, A


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small problem for smoke testing")
    ap.add_argument("--n", type=int, default=None,
                    help="grid points per dimension (default 128; quick 32)")
    ap.add_argument("--rtol", type=float, default=1e-6)
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions (median + spread reported)")
    ap.add_argument("--log-view", action="store_true",
                    help="print the -log_view solve/kernel-traffic "
                         "summary after the JSON line")
    ap.add_argument("--serving", action="store_true",
                    help="additionally run the coalesced-serving "
                         "episode (SolveServer vs sequential dispatch) "
                         "and print its JSON line")
    opts = ap.parse_args()
    nx = opts.n or (32 if opts.quick else 128)

    import jax

    ndev = len(jax.devices())
    # stencil sharding needs nz % ndev == 0
    if nx % ndev != 0:
        nx = ((nx + ndev - 1) // ndev) * ndev
    n = nx ** 3

    iters, walls, x_tpu, b, res = tpu_solve(nx, opts.rtol, "jacobi",
                                            reps=opts.reps)
    mg_iters, mg_walls, x_mg, _, _ = tpu_solve(nx, opts.rtol, "mg",
                                               reps=opts.reps)
    hi = 520 if not opts.quick else 220
    pers = on_chip_rate(nx, reps=opts.reps, hi=hi)

    # batched multi-RHS kernel: k=8 delta-method episode — one iteration
    # serves 8 columns, so per-RHS-iteration cost should undercut k=1
    k_batch = 8
    pers_b = batched_delta(nx, k=k_batch, reps=opts.reps,
                           hi=220 if opts.quick else 320)
    per_b = statistics.median(pers_b)

    cpu_iters, cpu_wall, x_cpu, A = cpu_baseline(nx, b, opts.rtol)

    # residual parity check in fp64 on host
    bnorm = np.linalg.norm(b.astype(np.float64))
    r_tpu = np.linalg.norm(b.astype(np.float64) - A @ x_tpu.astype(np.float64))
    r_mg = np.linalg.norm(b.astype(np.float64) - A @ x_mg.astype(np.float64))
    r_cpu = np.linalg.norm(b.astype(np.float64) - A @ x_cpu)
    parity = bool(max(r_tpu, r_mg) <= 10 * max(r_cpu, opts.rtol * bnorm))

    wall = statistics.median(walls)
    mg_wall = statistics.median(mg_walls)
    per = statistics.median(pers)
    onchip = 1.0 / per if per > 0 else 0.0
    gbps = PASSES_PER_ITER * n * 4 / per / 1e9 if per > 0 else 0.0
    # per-kernel achieved-GB/s recording (utils/profiling): the composed
    # CG step's model traffic over its measured delta-method time — shows
    # up in the -log_view kernel-traffic table alongside the
    # decompose_stencil pieces
    from mpi_petsc4py_example_tpu.utils.profiling import (
        record_kernel_traffic)
    record_kernel_traffic(f"cg_step[{nx}^3]", PASSES_PER_ITER * n * 4, per)
    # the batched kernel's achieved-GB/s row: same 11-pass model per
    # column, k columns per batched iteration — this is the line the
    # -log_view kernel-traffic table shows for the multi-RHS pipeline
    gbps_b = (PASSES_PER_ITER * n * 4 * k_batch / per_b / 1e9
              if per_b > 0 else 0.0)
    record_kernel_traffic(f"cg_many_step[k={k_batch},{nx}^3]",
                          PASSES_PER_ITER * n * 4 * k_batch, per_b)
    # headline: best time-to-rtol config (CG+MG) vs the CPU oracle
    best_wall = min(wall, mg_wall)
    line = {
        "metric": f"CG 3D Poisson {nx}^3 ({n:,} DoF) fp32: on-chip CG+Jacobi "
                  f"iteration rate (delta method, fixed tunnel launch "
                  f"latency excluded); vs_baseline is end-to-end "
                  f"time-to-rtol={opts.rtol:g} incl. launch latency, best "
                  f"config, vs scipy fp64 CPU",
        "value": round(onchip, 1),
        "unit": "iters/s",
        "vs_baseline": round(cpu_wall / best_wall, 3) if best_wall > 0 else 0.0,
        "extra": {
            "onchip_per_iter_us": round(1e6 * per, 1),
            "onchip_spread_us": [round(1e6 * min(pers), 1),
                                 round(1e6 * max(pers), 1)],
            "achieved_gbps": round(gbps, 1),
            "hbm_roof_frac": round(gbps / HBM_ROOF_GBPS, 3),
            # apparent traffic above the HBM roof means the CG state stayed
            # VMEM-resident across loop iterations (possible up to ~16 MB
            # vectors) — the 11-pass HBM model doesn't apply at that size
            "vmem_resident": bool(gbps > HBM_ROOF_GBPS),
            "batched_k8_onchip_per_iter_us": round(1e6 * per_b, 1),
            "batched_k8_per_rhs_iter_us": round(1e6 * per_b / k_batch, 1),
            "batched_k8_achieved_gbps": round(gbps_b, 1),
            "e2e_jacobi_wall_s": round(wall, 4),
            "e2e_jacobi_spread_s": [round(min(walls), 4),
                                    round(max(walls), 4)],
            "e2e_jacobi_iters": iters,
            "e2e_mg_wall_s": round(mg_wall, 4),
            "e2e_mg_iters": mg_iters,
            "e2e_iters_per_s": round(iters / wall, 1) if wall > 0 else 0.0,
            "cpu_wall_s": round(cpu_wall, 4), "cpu_iters": cpu_iters,
            "rel_residual_tpu": float(r_tpu / bnorm),
            "rel_residual_mg": float(r_mg / bnorm),
            "rel_residual_cpu": float(r_cpu / bnorm),
            "residual_parity": parity,
            "devices": len(jax.devices()),
            "platform": jax.devices()[0].platform,
        },
    }
    print(json.dumps(line))
    if opts.serving:
        serving_episode(nx if opts.quick else min(nx, 64),
                        requests=32 if opts.quick else 64,
                        rtol=opts.rtol)
    if opts.log_view:
        from mpi_petsc4py_example_tpu.utils import profiling
        profiling.log_view()
    return 0


if __name__ == "__main__":
    sys.exit(main())
