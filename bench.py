#!/usr/bin/env python
"""Benchmark harness — creates the baseline BASELINE.md says doesn't exist.

Headline metric (BASELINE.json): KSP iterations/second and time-to-rtol=1e-6
for CG on the 3D 7-point Poisson operator, with residual parity vs a CPU
oracle. The TPU path runs the matrix-free stencil operator (fp32, Jacobi-CG,
one jit-compiled program); the baseline is scipy.sparse.linalg.cg (fp64 CPU)
on the identical problem and tolerance — the stand-in for 8-rank PETSc KSPCG
(petsc4py is not installable here; scipy is the only CPU oracle, SURVEY.md §4).

Prints ONE JSON line:
  {"metric": ..., "value": iters_per_sec, "unit": "iters/s",
   "vs_baseline": cpu_time / tpu_time}

Usage: python bench.py [--quick] [--n NX] [--rtol R]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

# importing the package first applies TPU_SOLVE_PLATFORM / x64 config before
# any jax backend initialization (needed for forced-CPU smoke runs)
import mpi_petsc4py_example_tpu  # noqa: F401


def tpu_solve(nx: int, rtol: float, pc_type: str = "jacobi"):
    """CG on matrix-free stencil Poisson; returns (iters, wall, x, b, res)."""
    import jax.numpy as jnp

    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import StencilPoisson3D

    comm = tps.DeviceComm()
    # nz must divide the device count; nx is chosen accordingly by main()
    op = StencilPoisson3D(comm, nx, dtype=jnp.float32)
    n = nx ** 3
    rng = np.random.default_rng(7)
    x_true = rng.random(n).astype(np.float32)
    b = np.asarray(op.mult(tps.Vec.from_global(comm, x_true)).to_numpy())

    ksp = tps.KSP().create(comm)
    ksp.set_operators(op)
    ksp.set_type("cg")
    ksp.get_pc().set_type(pc_type)
    ksp.set_tolerances(rtol=rtol, atol=0.0, max_it=20000)

    x, bv = op.get_vecs()
    bv.set_global(b)
    ksp.solve(bv, x)          # warm-up: compiles the program
    x.zero()
    t0 = time.perf_counter()
    res = ksp.solve(bv, x)
    wall = time.perf_counter() - t0
    return res.iterations, wall, x.to_numpy(), b, res


def cpu_baseline(nx: int, b: np.ndarray, rtol: float):
    """scipy fp64 CG on the identical operator/tolerance."""
    import scipy.sparse.linalg as spla

    from mpi_petsc4py_example_tpu.models import poisson3d_csr

    A = poisson3d_csr(nx).astype(np.float64)
    bb = b.astype(np.float64)
    iters = [0]

    def cb(_):
        iters[0] += 1

    # Jacobi preconditioning to match the TPU configuration (diag = 6)
    M = spla.LinearOperator(A.shape, matvec=lambda v: v / 6.0)
    t0 = time.perf_counter()
    x, info = spla.cg(A, bb, rtol=rtol, atol=0.0, maxiter=20000,
                      M=M, callback=cb)
    wall = time.perf_counter() - t0
    return iters[0], wall, x, A


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small problem for smoke testing")
    ap.add_argument("--n", type=int, default=None,
                    help="grid points per dimension (default 128; quick 32)")
    ap.add_argument("--rtol", type=float, default=1e-6)
    opts = ap.parse_args()
    nx = opts.n or (32 if opts.quick else 128)

    import jax

    ndev = len(jax.devices())
    # stencil sharding needs nz % ndev == 0
    if nx % ndev != 0:
        nx = ((nx + ndev - 1) // ndev) * ndev

    iters, wall, x_tpu, b, res = tpu_solve(nx, opts.rtol, pc_type="jacobi")
    mg_iters, mg_wall, x_mg, _, _ = tpu_solve(nx, opts.rtol, pc_type="mg")

    cpu_iters, cpu_wall, x_cpu, A = cpu_baseline(nx, b, opts.rtol)

    # residual parity check in fp64 on host
    bnorm = np.linalg.norm(b.astype(np.float64))
    r_tpu = np.linalg.norm(b.astype(np.float64) - A @ x_tpu.astype(np.float64))
    r_mg = np.linalg.norm(b.astype(np.float64) - A @ x_mg.astype(np.float64))
    r_cpu = np.linalg.norm(b.astype(np.float64) - A @ x_cpu)
    parity = bool(max(r_tpu, r_mg) <= 10 * max(r_cpu, opts.rtol * bnorm))

    # headline: best time-to-rtol config (CG+MG) vs the CPU oracle
    best_wall = min(wall, mg_wall)
    iters_per_sec = iters / wall if wall > 0 else 0.0
    line = {
        "metric": f"CG time-to-rtol={opts.rtol:g}, 3D Poisson {nx}^3 "
                  f"({nx**3:,} DoF); iters/sec is the CG+Jacobi rate",
        "value": round(iters_per_sec, 2),
        "unit": "iters/s",
        "vs_baseline": round(cpu_wall / best_wall, 3) if best_wall > 0 else 0.0,
        "extra": {
            "tpu_jacobi_wall_s": round(wall, 4), "tpu_jacobi_iters": iters,
            "tpu_mg_wall_s": round(mg_wall, 4), "tpu_mg_iters": mg_iters,
            "cpu_wall_s": round(cpu_wall, 4), "cpu_iters": cpu_iters,
            "rel_residual_tpu": float(r_tpu / bnorm),
            "rel_residual_mg": float(r_mg / bnorm),
            "rel_residual_cpu": float(r_cpu / bnorm),
            "residual_parity": parity,
            "devices": len(jax.devices()),
            "platform": jax.devices()[0].platform,
        },
    }
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
