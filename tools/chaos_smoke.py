#!/usr/bin/env python
"""Chaos smoke: silent-corruption AND device-eviction drills for CI.

Silent-corruption drills (ISSUE 5 satellite) run a CG solve under
silent-corruption fault specs and assert the full detection ->
rollback -> recovery -> verification chain:

* a detector fired (ABFT checksum / drift gate / sentinel — the
  recovery trail carries its name);
* the recovered answer's fp64 TRUE relative residual meets rtol;
* the iterate matches the manufactured solution.

Device-eviction drills (``--evict``, ISSUE 8 satellite) arm a PERMANENT
``device.lost`` fault mid-solve and mid-serving-load and assert the
elastic escalation (resilience/elastic.py):

* the solve/serving session recovers onto a STRICTLY SMALLER mesh
  (a ``mesh_shrink`` recovery event with old > new device counts);
* the resumed solve provably continued from the checkpointed iterate,
  not iteration 0 (the shrink event's resumed iteration, and fewer
  remaining iterations than a cold start);
* every pending serving request resolves — a converged fp64-parity
  result, DEADLINE_EXCEEDED, or ServerOverloadedError — never a hung
  future or a dead dispatcher.

Exit status is NONZERO on any failed drill — the CI contract that
neither silent corruption nor hardware loss can silently regress.

Modes:

* ``TPU_SOLVE_FAULTS`` set in the environment: ONE corruption drill
  under exactly that spec (the env-activation route);
* ``--evict``: the two device-eviction drills via ``inject_faults``;
* ``--sstep`` (ISSUE 15): a bitflip armed INSIDE an s-step block (the
  basis-build applies checked by the one stacked Gram psum's ABFT
  partials) must detect -> roll back to the verified carry -> re-enter
  to an fp64-parity answer, and the ill-conditioned-monomial-basis
  drill must restart, exhaust ``-ksp_sstep_max_replacements``, and
  DEMOTE to classic CG (a ``sstep_demote`` RecoveryEvent) while still
  converging;
* ``--fleet`` (ISSUE 13): the loss -> shrink -> heal -> RE-GROW round
  trip — a retry-ladder drill proving the re-grown mesh RESUMES the
  solve past iteration 0, and a mixed-QoS router drill with one
  injected ``device.lost`` AND one ``heal()`` mid-load, exiting nonzero
  unless every future resolves and post-heal capacity returns;
* ``--multisplit`` (ISSUE 17): the asynchronous-tier drills — a sticky
  slow device (``comm.delay`` timing fault) must be absorbed as bounded
  staleness (resyncs fire, the solve converges to strict fp64 parity);
  a mid-solve ``device.lost`` must degrade to ONE stale block and
  re-home it, with every block's published version sequence strictly
  increasing across the loss (survivors provably never revisit
  iteration 0); and an ``exchange.put`` drop/partition must only ever
  cost staleness, never correctness;
* ``--transport`` (ISSUE 20): the multi-host RPC drills — killing the
  owning replica host mid-load must fail every in-flight future over
  to the survivor with the re-homed solve RESUMING past iteration 0
  from the shipped elastic checkpoint; injected duplicate delivery
  (a lost reply forcing a retry, and a doubled request) must execute
  each logical call exactly once — the idempotency cache absorbs the
  duplicate, the future never double-resolves; and a partition struck
  during a live migration must leave a truthful placement (src still
  serves at parity) and, once healed, ``reconcile()`` must converge the
  fleet to a single owner with the orphaned copy unregistered;
* ``--persistent`` (ISSUE 18): the device-resident request-queue
  drills — a silent bitflip armed across a fully-staged persistent
  launch must resolve EVERY slot future with no silently-wrong answer
  (a slot either converges to strict fp64 parity or honestly reports
  non-convergence — the verified-residual exit gate is the detector),
  and a mid-launch ``device.lost`` must resolve every slot through the
  elastic tier (resuming past iteration 0), shrink the server's mesh,
  and REBUILD the resident program on the surviving geometry for
  post-recovery traffic;
* neither: the builtin silent-corruption sweep over every silent fault
  kind at every injectable point (spmv.result / pc.apply / comm.psum).

``--trace-out <path>`` (composable with every mode) arms the telemetry
layer for the run and exports the Chrome/Perfetto trace afterwards,
with the flight-recorder ring dumped next to it (``<path>.flight.json``)
— then VALIDATES both: the trace must be non-empty and schema-clean,
and (under ``--evict``) must contain the retry -> shrink span chain with
the resumed iteration number as a span attribute, the ISSUE-11
acceptance drill. Exit status stays nonzero on any validation miss.
"""

from __future__ import annotations

import os
import sys

# the eviction drills need a multi-device mesh to shrink; force the
# 8-virtual-device CPU host platform (the tests/conftest.py idiom) BEFORE
# any jax import — harmless when real accelerator devices take precedence
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RTOL = 1e-10

#: the builtin sweep: every silent kind at every injectable point
#: (at=2 targets the loop apply; times=1 lets the retry re-trace clean)
BUILTIN_SPECS = (
    "spmv.result=bitflip:at=2:times=1",
    "spmv.result=scale:mag=1e-3:at=2:times=1",
    "pc.apply=bitflip:at=2:times=1",
    "pc.apply=scale:mag=1e-2:at=2:times=1",
    "comm.psum=corrupt:times=1:at=3",
)


def drill(label: str, ctx) -> list[str]:
    """One corruption drill; returns a list of failure descriptions."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr

    comm = tps.DeviceComm()
    A = poisson2d_csr(12)
    M = tps.Mat.from_scipy(comm, A)
    ksp = tps.KSP().create(comm)
    ksp.set_operators(M)
    ksp.set_type("cg")
    ksp.get_pc().set_type("jacobi")
    ksp.set_tolerances(rtol=RTOL)
    ksp.abft = True
    ksp.residual_replacement = 10
    x_true = np.random.default_rng(0).random(A.shape[0])
    b = A @ x_true
    x, bv = M.get_vecs()
    bv.set_global(b)

    problems: list[str] = []
    with ctx:
        res = tps.resilient_solve(
            ksp, bv, x, tps.RetryPolicy(sleep=lambda _d: None))
    detectors = [e.detector for e in res.recovery_events
                 if e.kind == "fault" and e.detector]
    if not detectors:
        problems.append("corruption went UNDETECTED (no detector event)")
    if not res.converged:
        problems.append(f"recovered solve did not converge: {res}")
    if not any(e.kind == "verify" for e in res.recovery_events):
        problems.append("no post-recovery true-residual verification ran")
    rtrue = (np.linalg.norm(b - A @ x.to_numpy())
             / np.linalg.norm(b))
    if not rtrue <= RTOL * 1.05:
        problems.append(f"true relative residual {rtrue:.3e} misses rtol")
    if not np.allclose(x.to_numpy(), x_true, atol=1e-7):
        problems.append("recovered iterate differs from the manufactured "
                        "solution")
    status = "OK" if not problems else "FAIL"
    print(f"[chaos] {label}: {status} detectors={detectors} "
          f"attempts={res.attempts} true_rres={rtrue:.3e}")
    return [f"{label}: {p}" for p in problems]


def drill_megasolve() -> list[str]:
    """Silent corruption INSIDE the fused whole-solve loop
    (``--megasolve``, ISSUE 12 satellite): with ``-ksp_megasolve`` the
    entire refinement/verification recurrence is ONE compiled program —
    a bitflip armed on the inner CG's operator apply must be detected by
    the nested guarded plan loop's ABFT channel, freeze the fused outer
    recurrence, surface the verified-iterate carry (the rollback
    target), and recover through the resilient ladder to an fp64-parity
    answer — at exactly ONE compiled-program launch per attempt, proven
    from the telemetry dispatch counter (detection -> rollback ->
    re-entry still costs one dispatch each way)."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.utils.profiling import dispatch_counts

    problems: list[str] = []
    comm = tps.DeviceComm()
    A = poisson2d_csr(12)
    M = tps.Mat.from_scipy(comm, A)
    ksp = tps.KSP().create(comm)
    ksp.set_operators(M)
    ksp.set_type("cg")
    ksp.get_pc().set_type("jacobi")
    ksp.set_tolerances(rtol=RTOL)
    ksp.megasolve = True
    ksp.abft = True
    ksp.residual_replacement = 10
    x_true = np.random.default_rng(0).random(A.shape[0])
    b = A @ x_true
    x, bv = M.get_vecs()
    bv.set_global(b)
    before = dispatch_counts()
    with tps.inject_faults("spmv.result=bitflip:at=2:times=1"):
        res = tps.resilient_solve(
            ksp, bv, x, tps.RetryPolicy(sleep=lambda _d: None))
    after = dispatch_counts()
    mega = int(after.get("megasolve", 0) - before.get("megasolve", 0))
    other = int(sum(after.values()) - sum(before.values())) - mega
    detectors = [e.detector for e in res.recovery_events
                 if e.kind == "fault" and e.detector]
    if not detectors:
        problems.append("fused-loop corruption went UNDETECTED")
    if not any(e.kind == "rollback" for e in res.recovery_events):
        problems.append("no rollback re-entry in the recovery trail")
    if not any(e.kind == "verify" for e in res.recovery_events):
        problems.append("no post-recovery true-residual verification ran")
    if not res.converged:
        problems.append(f"recovered fused solve did not converge: {res}")
    if mega != res.attempts:
        problems.append(
            f"{mega} fused launches for {res.attempts} attempt(s) — the "
            "one-dispatch-per-attempt contract broke under fire")
    if other != 0:
        problems.append(f"{other} UNFUSED program launch(es) on the "
                        "megasolve path")
    rtrue = (np.linalg.norm(b - A @ x.to_numpy()) / np.linalg.norm(b))
    if not rtrue <= RTOL * 1.05:
        problems.append(f"true relative residual {rtrue:.3e} misses rtol")
    if not np.allclose(x.to_numpy(), x_true, atol=1e-7):
        problems.append("recovered iterate differs from the manufactured "
                        "solution")
    status = "OK" if not problems else "FAIL"
    print(f"[chaos] megasolve: {status} detectors={detectors} "
          f"attempts={res.attempts} fused_launches={mega} "
          f"true_rres={rtrue:.3e}")
    return [f"megasolve: {p}" for p in problems]


def drill_sstep() -> list[str]:
    """Silent corruption INSIDE an s-step block (``--sstep``, ISSUE 15
    satellite): a bitflip armed on a basis-build operator apply must be
    detected by the ABFT partials riding the block's ONE stacked Gram
    psum, roll the iterate back to the VERIFIED carry, and recover
    through the resilient ladder (rollback -> re-entry -> independent
    re-verification) to an fp64-parity answer — the PR-5 chain proven
    inside the communication-avoiding schedule."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr

    problems: list[str] = []
    comm = tps.DeviceComm()
    A = poisson2d_csr(12)
    M = tps.Mat.from_scipy(comm, A)
    ksp = tps.KSP().create(comm)
    ksp.set_operators(M)
    ksp.set_type("sstep")
    ksp.sstep_s = 4
    ksp.get_pc().set_type("jacobi")
    ksp.set_tolerances(rtol=RTOL)
    ksp.abft = True
    ksp.residual_replacement = 12
    x_true = np.random.default_rng(0).random(A.shape[0])
    b = A @ x_true
    x, bv = M.get_vecs()
    bv.set_global(b)
    # at=2 lands on the FIRST block's P-chain basis apply (the init
    # residual is spmv site 1) — corruption inside the s-block proper
    with tps.inject_faults("spmv.result=bitflip:at=2:times=1"):
        res = tps.resilient_solve(
            ksp, bv, x, tps.RetryPolicy(sleep=lambda _d: None))
    detectors = [e.detector for e in res.recovery_events
                 if e.kind == "fault" and e.detector]
    if not detectors:
        problems.append("s-block corruption went UNDETECTED")
    if not any(e.kind == "rollback" for e in res.recovery_events):
        problems.append("no rollback to the verified carry in the "
                        "recovery trail")
    if not any(e.kind == "verify" for e in res.recovery_events):
        problems.append("no post-recovery true-residual verification ran")
    if not res.converged:
        problems.append(f"recovered s-step solve did not converge: {res}")
    if any(e.kind == "sstep_demote" for e in res.recovery_events):
        problems.append("healthy-basis drill DEMOTED to classic cg")
    rtrue = (np.linalg.norm(b - A @ x.to_numpy()) / np.linalg.norm(b))
    if not rtrue <= RTOL * 1.05:
        problems.append(f"true relative residual {rtrue:.3e} misses rtol")
    if not np.allclose(x.to_numpy(), x_true, atol=1e-7):
        problems.append("recovered iterate differs from the manufactured "
                        "solution")
    status = "OK" if not problems else "FAIL"
    print(f"[chaos] sstep: {status} detectors={detectors} "
          f"attempts={res.attempts} true_rres={rtrue:.3e}")
    failures = [f"sstep: {p}" for p in problems]

    # ---- the demotion half: an ill-conditioned monomial basis at
    # large s must restart, exhaust -ksp_sstep_max_replacements, and
    # DEMOTE to classic CG with a RecoveryEvent — and still converge
    from mpi_petsc4py_example_tpu.models import tridiag_family
    A2 = tridiag_family(384)
    M2 = tps.Mat.from_scipy(comm, A2)
    b2 = np.asarray(A2 @ np.random.default_rng(5).random(384))
    k2 = tps.KSP().create(comm)
    k2.set_operators(M2)
    k2.set_type("sstep")
    k2.sstep_s = 12
    k2.get_pc().set_type("none")
    k2.set_tolerances(rtol=1e-12, max_it=8000)
    k2.residual_replacement = 24
    k2.sstep_max_replacements = 1
    x2, bv2 = M2.get_vecs()
    bv2.set_global(b2)
    res2 = k2.solve(bv2, x2)
    dem = [e for e in res2.recovery_events if e.kind == "sstep_demote"]
    problems2: list[str] = []
    if not dem:
        problems2.append("ill-conditioned basis never demoted")
    if not res2.converged:
        problems2.append(f"demoted solve did not converge: {res2}")
    r2 = (np.linalg.norm(b2 - A2 @ x2.to_numpy()) / np.linalg.norm(b2))
    if not r2 <= 1e-11:
        problems2.append(f"demoted answer residual {r2:.3e} misses rtol")
    status2 = "OK" if not problems2 else "FAIL"
    print(f"[chaos] sstep-demote: {status2} demotions={len(dem)} "
          f"iters={res2.iterations} true_rres={r2:.3e}")
    return failures + [f"sstep-demote: {p}" for p in problems2]


def drill_evict_solve() -> list[str]:
    """Permanent device loss MID-SOLVE: the elastic escalation must land
    the solve on a strictly smaller mesh, resumed from the checkpointed
    iterate, with the answer at fp64 parity."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.resilience import faults as _faults

    problems: list[str] = []
    comm = tps.DeviceComm()
    if comm.size < 2:
        return ["evict-solve: needs a multi-device mesh "
                f"(got {comm.size} device[s])"]
    A = poisson2d_csr(16)

    def make_session():
        M = tps.Mat.from_scipy(comm, A)
        ksp = tps.KSP().create(comm)
        ksp.set_operators(M)
        ksp.set_type("cg")
        ksp.get_pc().set_type("jacobi")
        ksp.set_tolerances(rtol=RTOL)
        x, bv = M.get_vecs()
        return ksp, x, bv

    x_true = np.random.default_rng(0).random(A.shape[0])
    b = A @ x_true
    # cold baseline (same geometry ladder end state is smaller, but the
    # iteration count to beat is the uninterrupted one)
    ksp0, x0, bv0 = make_session()
    bv0.set_global(b)
    cold = ksp0.solve(bv0, x0)

    ksp, x, bv = make_session()
    bv.set_global(b)
    victim = comm.device_ids[-1]
    spec = f"device.lost=unavailable:device={victim}:iter=15"
    try:
        with tps.inject_faults(spec):
            res = tps.resilient_solve(
                ksp, bv, x, tps.RetryPolicy(sleep=lambda _d: None),
                elastic=tps.ElasticPolicy(max_same_mesh_retries=1))
        shrinks = [e for e in res.recovery_events
                   if e.kind == "mesh_shrink"]
        if not shrinks:
            problems.append("no mesh_shrink recovery event")
        elif not shrinks[0].new_devices < shrinks[0].old_devices:
            problems.append(f"mesh did not shrink: {shrinks[0]}")
        elif shrinks[0].iterations <= 0:
            problems.append("resumed from iteration 0, not the "
                            "checkpointed iterate")
        if ksp.comm.size >= comm.size:
            problems.append(f"session still on {ksp.comm.size} devices")
        if not res.converged:
            problems.append(f"recovered solve did not converge: {res}")
        if not res.iterations < cold.iterations:
            problems.append(
                f"resumed solve took {res.iterations} iterations, not "
                f"fewer than the {cold.iterations}-iteration cold start")
        rtrue = (np.linalg.norm(b - A @ x.to_numpy())
                 / np.linalg.norm(b))
        if not rtrue <= RTOL * 1.05:
            problems.append(f"true relative residual {rtrue:.3e} "
                            "misses rtol")
        print(f"[chaos] evict-solve: "
              f"{'OK' if not problems else 'FAIL'} "
              f"{comm.size}->{ksp.comm.size} devices, "
              f"iters {res.iterations} (cold {cold.iterations}), "
              f"true_rres={rtrue:.3e}")
    finally:
        _faults.heal()
    return [f"evict-solve: {p}" for p in problems]


def drill_evict_serving() -> list[str]:
    """Permanent device loss MID-SERVING-LOAD: the server must adopt the
    degraded mesh and EVERY pending future must resolve — a converged
    fp64-parity result, DEADLINE_EXCEEDED, or ServerOverloadedError —
    with the dispatcher alive for post-recovery traffic."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.resilience import faults as _faults
    from mpi_petsc4py_example_tpu.serving import SolveServer

    problems: list[str] = []
    comm = tps.DeviceComm()
    if comm.size < 2:
        return ["evict-serving: needs a multi-device mesh "
                f"(got {comm.size} device[s])"]
    A = poisson2d_csr(12)
    n = A.shape[0]
    rng = np.random.default_rng(8)
    R = 12
    Xt = rng.random((n, R))
    B = np.asarray(A @ Xt)
    victim = comm.device_ids[-1]
    srv = SolveServer(
        comm, window=0.005, max_k=4, max_queue=64, deadline=120.0,
        retry_policy=tps.RetryPolicy(sleep=lambda _d: None),
        autostart=False)
    try:
        srv.register_operator("poisson", A, rtol=RTOL)
        futs = [srv.submit("poisson", B[:, j]) for j in range(R)]
        # the loss fires at the 2nd solve-program boundary: some blocks
        # complete on the full mesh, the rest ride the shrink
        with tps.inject_faults(
                f"device.lost=unavailable:device={victim}:at=2:iter=6"):
            srv.start()
            if not srv.drain(600):
                problems.append("drain timed out — hung future(s)")
        answered = converged = typed = 0
        for j, f in enumerate(futs):
            if not f.done():
                problems.append(f"request {j} future never resolved")
                continue
            answered += 1
            exc = f.exception(0)
            if exc is None:
                r = f.result(0)
                rres = (np.linalg.norm(B[:, j] - A @ r.x)
                        / np.linalg.norm(B[:, j]))
                if not (r.converged and rres <= RTOL * 1.05):
                    problems.append(
                        f"request {j}: reason={r.reason_name} "
                        f"true_rres={rres:.3e} (parity miss)")
                else:
                    converged += 1
            elif isinstance(exc, (tps.DeadlineExceededError,
                                  tps.ServerOverloadedError)):
                typed += 1
            else:
                problems.append(f"request {j}: untyped failure {exc!r}")
        st = srv.stats()
        if not st["mesh_shrinks"]:
            problems.append("server never adopted a shrunk mesh")
        if srv.comm.size >= comm.size:
            problems.append(f"server still on {srv.comm.size} devices")
        if converged == 0:
            problems.append("no request converged across the shrink")
        # the dispatcher must survive: post-recovery traffic still served
        post = srv.solve("poisson", B[:, 0], timeout=300)
        rres = (np.linalg.norm(B[:, 0] - A @ post.x)
                / np.linalg.norm(B[:, 0]))
        if not (post.converged and rres <= RTOL * 1.05):
            problems.append(f"post-recovery request failed parity "
                            f"({post.reason_name}, {rres:.3e})")
        print(f"[chaos] evict-serving: "
              f"{'OK' if not problems else 'FAIL'} "
              f"{comm.size}->{srv.comm.size} devices, {answered}/{R} "
              f"answered ({converged} converged, {typed} typed errors), "
              f"shrinks={len(st['mesh_shrinks'])}")
    finally:
        srv.shutdown(wait=False)
        _faults.heal()
    return [f"evict-serving: {p}" for p in problems]


def _persistent_server(tps, comm, A, rtol):
    from mpi_petsc4py_example_tpu.serving import SolveServer
    srv = SolveServer(
        comm, window=0.005, max_k=8, autostart=False,
        retry_policy=tps.RetryPolicy(sleep=lambda _d: None))
    srv.register_operator("poisson", A, ksp_type="cg", pc_type="jacobi",
                          rtol=rtol, persistent=True)
    return srv


def drill_persistent_bitflip() -> list[str]:
    """Silent bitflip across a fully-staged persistent launch
    (``--persistent``, ISSUE 18): with a fault plan armed the runner
    routes the whole launch through the resilient per-batch path, the
    flip corrupts ONE slot's inner recurrence, and the megasolve
    verified-residual exit gate is the detector — the poisoned slot
    must either converge to strict fp64 parity (the fp64 refinement
    outer absorbed the flip) or honestly report non-convergence; a
    CONVERGED slot that misses parity is the silent lie this drill
    exists to catch. Every one of the Q slot futures must resolve, and
    the resident program must serve post-fault traffic on the direct
    path."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.utils.profiling import dispatch_counts

    problems: list[str] = []
    comm = tps.DeviceComm()
    A = poisson2d_csr(12)
    n = A.shape[0]
    rng = np.random.default_rng(9)
    Q = 8
    Xt = rng.random((n, Q))
    B = np.asarray(A @ Xt)
    srv = _persistent_server(tps, comm, A, RTOL)
    try:
        with tps.inject_faults("spmv.result=bitflip:at=2:times=1"):
            futs = [srv.submit("poisson", B[:, j]) for j in range(Q)]
            srv.start()
            if not srv.drain(600):
                problems.append("drain timed out — hung slot future(s)")
        answered = parity = honest = 0
        for j, f in enumerate(futs):
            if not f.done():
                problems.append(f"slot {j} future never resolved")
                continue
            answered += 1
            exc = f.exception(0)
            if exc is not None:
                problems.append(f"slot {j}: untyped failure {exc!r}")
                continue
            r = f.result(0)
            rres = (np.linalg.norm(B[:, j] - A @ r.x)
                    / np.linalg.norm(B[:, j]))
            if r.converged:
                if rres <= RTOL * 1.05:
                    parity += 1
                else:
                    problems.append(
                        f"slot {j}: CONVERGED with true_rres "
                        f"{rres:.3e} — a silently wrong answer")
            else:
                honest += 1        # the gate refused to lie
        st = srv.stats().get("persistent", {}).get("poisson", {})
        if st.get("fallbacks", 0) < 1:
            problems.append("armed plan never routed the launch through "
                            "the resilient fallback")
        # the plan is disarmed: post-fault traffic rides the DIRECT
        # resident program again, at ≤ one dispatch for the request
        before = dispatch_counts()
        post = srv.solve("poisson", B[:, 0], timeout=300)
        after = dispatch_counts()
        direct = int(after.get("persistent_serve", 0)
                     - before.get("persistent_serve", 0))
        rres = (np.linalg.norm(B[:, 0] - A @ post.x)
                / np.linalg.norm(B[:, 0]))
        if not (post.converged and rres <= RTOL * 1.05):
            problems.append(f"post-fault request failed parity "
                            f"({post.reason_name}, {rres:.3e})")
        if direct != 1:
            problems.append(f"post-fault request cost {direct} "
                            "persistent_serve dispatch(es), wanted 1")
        print(f"[chaos] persistent-bitflip: "
              f"{'OK' if not problems else 'FAIL'} {answered}/{Q} "
              f"answered ({parity} fp64-parity, {honest} honestly "
              f"non-converged), fallbacks={st.get('fallbacks')}")
    finally:
        srv.shutdown(wait=False)
    return [f"persistent-bitflip: {p}" for p in problems]


def drill_persistent_lost() -> list[str]:
    """Mid-launch device loss on a persistent session (``--persistent``,
    ISSUE 18): the loss fires at the resilient fallback's program
    boundary, the elastic tier shrinks the mesh RESUMING past iteration
    0, every slot future resolves converged at fp64 parity, the server
    adopts the shrunk mesh, and the NEXT launch transparently rebuilds
    the resident program on the surviving geometry
    (``stats['rebuilds']``)."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.resilience import faults as _faults

    problems: list[str] = []
    comm = tps.DeviceComm()
    if comm.size < 2:
        return ["persistent-lost: needs a multi-device mesh "
                f"(got {comm.size} device[s])"]
    A = poisson2d_csr(12)
    n = A.shape[0]
    rng = np.random.default_rng(10)
    Q = 6
    Xt = rng.random((n, Q))
    B = np.asarray(A @ Xt)
    victim = comm.device_ids[-1]
    srv = _persistent_server(tps, comm, A, RTOL)
    try:
        spec = f"device.lost=unavailable:device={victim}:at=1:iter=10"
        with tps.inject_faults(spec):
            futs = [srv.submit("poisson", B[:, j]) for j in range(Q)]
            srv.start()
            if not srv.drain(600):
                problems.append("drain timed out — hung slot future(s)")
        for j, f in enumerate(futs):
            if not f.done():
                problems.append(f"slot {j} future never resolved")
                continue
            exc = f.exception(0)
            if exc is not None:
                problems.append(f"slot {j}: untyped failure {exc!r}")
                continue
            r = f.result(0)
            rres = (np.linalg.norm(B[:, j] - A @ r.x)
                    / np.linalg.norm(B[:, j]))
            if not (r.converged and rres <= RTOL * 1.05):
                problems.append(f"slot {j}: reason={r.reason_name} "
                                f"true_rres={rres:.3e} (parity miss)")
        st = srv.stats()
        if not st["mesh_shrinks"]:
            problems.append("server never adopted a shrunk mesh")
        elif st["mesh_shrinks"][0]["resumed_iteration"] <= 0:
            problems.append("shrunk solve restarted from iteration 0 — "
                            "the checkpoint carry was lost")
        if srv.comm.size >= comm.size:
            problems.append(f"server still on {srv.comm.size} devices")
        # the registry still holds the victim, but the adopted mesh
        # excludes it: the next launch must take the DIRECT path and
        # rebuild the resident program for the shrunk geometry
        post = srv.solve("poisson", B[:, 0], timeout=600)
        rres = (np.linalg.norm(B[:, 0] - A @ post.x)
                / np.linalg.norm(B[:, 0]))
        if not (post.converged and rres <= RTOL * 1.05):
            problems.append(f"post-shrink request failed parity "
                            f"({post.reason_name}, {rres:.3e})")
        pst = srv.stats().get("persistent", {}).get("poisson", {})
        if pst.get("rebuilds", 0) != 1:
            problems.append(
                f"{pst.get('rebuilds', 0)} resident-program rebuild(s) "
                "after the shrink, wanted exactly 1")
        print(f"[chaos] persistent-lost: "
              f"{'OK' if not problems else 'FAIL'} "
              f"{comm.size}->{srv.comm.size} devices, "
              f"resumed_iter="
            f"{st['mesh_shrinks'][0]['resumed_iteration'] if st['mesh_shrinks'] else '-'}, "
              f"rebuilds={pst.get('rebuilds')}")
    finally:
        srv.shutdown(wait=False)
        _faults.heal()
    return [f"persistent-lost: {p}" for p in problems]


def drill_fleet_regrow() -> list[str]:
    """Loss -> shrink -> heal -> RE-GROW in one resilient solve
    (``--fleet``, the elastic ladder's round trip): a sticky device loss
    shrinks the session (resuming past iteration 0), the heal lands
    mid-backoff, and the next transient failure re-grows it onto the
    repaired full mesh — where the solve again RESUMES from the
    checkpointed iterate, never iteration 0. The deterministic proof of
    the acceptance line 'solve resumes past iteration 0 on the re-grown
    mesh'."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.resilience import faults as _faults

    problems: list[str] = []
    comm = tps.DeviceComm()
    if comm.size < 2:
        return ["fleet-regrow: needs a multi-device mesh "
                f"(got {comm.size} device[s])"]
    A = poisson2d_csr(16)
    M = tps.Mat.from_scipy(comm, A)
    ksp = tps.KSP().create(comm)
    ksp.set_operators(M)
    ksp.set_type("cg")
    ksp.get_pc().set_type("jacobi")
    ksp.set_tolerances(rtol=RTOL)
    x_true = np.random.default_rng(0).random(A.shape[0])
    b = A @ x_true
    x, bv = M.get_vecs()
    bv.set_global(b)
    healed = []

    def sleep_heals(_d):
        # the repair arrives while the session runs degraded: the
        # backoff of the first post-shrink transient failure is the
        # deterministic host-side moment to apply it
        if not healed:
            healed.append(_faults.heal())

    victim = comm.device_ids[-1]
    spec = (f"device.lost=unavailable:device={victim}:at=1:iter=10,"
            "ksp.program=unavailable:at=2:times=2:iter=20")
    try:
        with tps.inject_faults(spec):
            res = tps.resilient_solve(ksp, bv, x,
                                      tps.RetryPolicy(sleep=sleep_heals))
        shrinks = [e for e in res.recovery_events
                   if e.kind == "mesh_shrink"]
        regrows = [e for e in res.recovery_events
                   if e.kind == "mesh_regrow"]
        if not shrinks:
            problems.append("no mesh_shrink recovery event")
        elif shrinks[0].iterations <= 0:
            problems.append("shrink resumed from iteration 0")
        if not regrows:
            problems.append("no mesh_regrow recovery event (heal was "
                            f"{healed})")
        else:
            g = regrows[0]
            if not g.new_devices > g.old_devices:
                problems.append(f"re-grow did not grow: {g}")
            if g.iterations <= 0:
                problems.append("solve did NOT resume past iteration 0 "
                                "on the re-grown mesh")
        if ksp.comm.size != comm.size:
            problems.append(f"capacity did not return: "
                            f"{ksp.comm.size}/{comm.size} devices")
        if not res.converged:
            problems.append(f"recovered solve did not converge: {res}")
        rtrue = (np.linalg.norm(b - A @ x.to_numpy())
                 / np.linalg.norm(b))
        if not rtrue <= RTOL * 1.05:
            problems.append(f"true relative residual {rtrue:.3e} misses "
                            "rtol")
        print(f"[chaos] fleet-regrow: "
              f"{'OK' if not problems else 'FAIL'} ladder "
              f"{comm.size}->{shrinks[0].new_devices if shrinks else '?'}"
              f"->{ksp.comm.size} devices, resumed at "
              f"{shrinks[0].iterations if shrinks else '?'} then "
              f"{regrows[0].iterations if regrows else '?'}, "
              f"true_rres={rtrue:.3e}")
    finally:
        _faults.heal()
    return [f"fleet-regrow: {p}" for p in problems]


def drill_fleet_serving() -> list[str]:
    """Mixed-QoS load on a router fleet with ONE injected device loss
    AND one heal mid-load (``--fleet``): every future must resolve (a
    converged fp64-parity result or a typed QoS error), the replica must
    shrink then RE-GROW, and post-heal capacity must return to the
    provisioned mesh with post-recovery traffic still served."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.resilience import faults as _faults
    from mpi_petsc4py_example_tpu.serving import SolveRouter

    problems: list[str] = []
    comm = tps.DeviceComm()
    if comm.size < 2:
        return ["fleet-serving: needs a multi-device mesh "
                f"(got {comm.size} device[s])"]
    A = poisson2d_csr(12)
    n = A.shape[0]
    rng = np.random.default_rng(14)
    R = 16
    Xt = rng.random((n, R))
    B = np.asarray(A @ Xt)
    victim = comm.device_ids[-1]
    rt = SolveRouter(2, comm, window=0.004, max_k=4, deadline=120.0,
                     retry_policy=tps.RetryPolicy(sleep=lambda _d: None))
    try:
        rt.register_operator("poisson", A, pc_type="jacobi", rtol=RTOL)
        # mixed-QoS Poisson-ish load: alternating classes, bursty gaps
        classes = ["interactive" if j % 2 else "bulk" for j in range(R)]
        futs = []
        # the loss fires at the 1st dispatched block under the armed
        # plan, with real partial state
        with tps.inject_faults(
                f"device.lost=unavailable:device={victim}:at=1:iter=6"):
            for j in range(R // 2):
                futs.append(rt.submit("poisson", B[:, j],
                                      qos=classes[j]))
            if not rt.drain(600):
                problems.append("drain timed out during the loss phase")
        st = rt.stats()
        if st["mesh_shrinks"] != 1:
            problems.append(f"expected 1 mesh shrink, saw "
                            f"{st['mesh_shrinks']}")
        # ONE heal mid-load: capacity must come back for the second half
        _faults.heal()
        regrown = rt.heal_check()
        for j in range(R // 2, R):
            futs.append(rt.submit("poisson", B[:, j], qos=classes[j]))
        if not rt.drain(600):
            problems.append("drain timed out during the heal phase")
        st = rt.stats()
        if regrown < 1 or st["mesh_regrows"] < 1:
            problems.append(f"no replica re-grew after the heal "
                            f"(regrown={regrown}, stats="
                            f"{st['mesh_regrows']})")
        sizes = [s["devices"] for s in st["per_replica"].values()]
        if any(sz != comm.size for sz in sizes):
            problems.append(f"post-heal capacity did not return: "
                            f"replica sizes {sizes} != {comm.size}")
        answered = converged = typed = 0
        for j, f in enumerate(futs):
            if not f.done():
                problems.append(f"request {j} future never resolved")
                continue
            answered += 1
            exc = f.exception(0)
            if exc is None:
                r = f.result(0)
                rres = (np.linalg.norm(B[:, j] - A @ r.x)
                        / np.linalg.norm(B[:, j]))
                if not (r.converged and rres <= RTOL * 1.05):
                    problems.append(
                        f"request {j} ({classes[j]}): "
                        f"reason={r.reason_name} true_rres={rres:.3e} "
                        "(parity miss)")
                else:
                    converged += 1
            elif isinstance(exc, (tps.DeadlineExceededError,
                                  tps.ServerOverloadedError)):
                typed += 1
            else:
                problems.append(f"request {j}: untyped failure {exc!r}")
        if converged == 0:
            problems.append("no request converged across the ladder")
        print(f"[chaos] fleet-serving: "
              f"{'OK' if not problems else 'FAIL'} {answered}/{R} "
              f"answered ({converged} converged, {typed} typed), "
              f"shrinks={st['mesh_shrinks']} regrows={st['mesh_regrows']} "
              f"replica sizes back to {sizes}")
    finally:
        rt.shutdown(wait=False)
        _faults.heal()
    return [f"fleet-serving: {p}" for p in problems]


def _multisplit_problem(n=256, nblocks=4, seed=3):
    """Block-diagonally-dominant model problem (the Frommer–Szyld
    convergence condition the async tier documents) + manufactured
    solution."""
    import scipy.sparse as sp

    A = sp.diags([-1.0, 4.0, -1.0], [-1, 0, 1], shape=(n, n),
                 format="csr")
    x_true = np.random.default_rng(seed).random(n)
    b = np.asarray(A @ x_true)
    return A, b, x_true, nblocks


def drill_multisplit_jitter() -> list[str]:
    """Sticky slow device under the async tier (``--multisplit``): a
    seeded ``comm.delay`` timing fault pins one block's device at +20 ms
    per step. The bounded-staleness supervisor must absorb it — resyncs
    fire, observed staleness stays within the bound — and the solve must
    still land at strict fp64 parity. Every synchronous plan pays this
    straggler at every reduction; cfg16 measures that crossover, this
    drill proves the tolerance machinery."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.resilience import faults as _faults
    from mpi_petsc4py_example_tpu.solvers.multisplit import MultisplitSolver

    problems: list[str] = []
    A, b, _x_true, nblocks = _multisplit_problem()
    bound = 3
    ms = MultisplitSolver(nblocks=nblocks, max_stale=bound, rtol=RTOL)
    ms.set_operator(A)
    slow = ms._blocks[1].device_id
    spec = f"comm.delay=delay:device={slow}:times=*:mean=0.02:seed=7"
    try:
        with tps.inject_faults(spec):
            res = ms.solve(b)
    finally:
        _faults.heal()
    if not res.converged:
        problems.append(f"jittered solve did not converge: {res}")
    if res.resyncs == 0:
        problems.append("sticky slow device never forced a resync — the "
                        "staleness bound is not being enforced")
    # ages grow by at most 1 per outer step, so the FIRST over-bound
    # read — the one that triggers the resync — records bound+1; any
    # age past that means a resync failed to pull the partner back
    if res.max_stale_seen > bound + 1:
        problems.append(f"observed staleness {res.max_stale_seen} "
                        f"exceeds the enforced bound {bound}+1")
    rtrue = np.linalg.norm(b - A @ res.x) / np.linalg.norm(b)
    if not rtrue <= RTOL:
        problems.append(f"true relative residual {rtrue:.3e} misses the "
                        "strict tolerance")
    status = "OK" if not problems else "FAIL"
    print(f"[chaos] multisplit-jitter: {status} cut={res.cut_version} "
          f"resyncs={res.resyncs} max_stale_seen={res.max_stale_seen} "
          f"true_rres={rtrue:.3e}")
    return [f"multisplit-jitter: {p}" for p in problems]


def drill_multisplit_lost() -> list[str]:
    """Mid-solve ``device.lost`` under the async tier (``--multisplit``,
    the ISSUE 17 acceptance drill): the solve must degrade to ONE stale
    block (survivors iterate against its frozen last-exchanged version),
    re-home the lost block onto a survivor, and converge to strict fp64
    tolerance — with every block's published version sequence strictly
    increasing across the loss. A restart-from-iteration-0 anywhere
    would publish a version at or below one already seen; the recorded
    sequences make 'survivors never revisit iteration 0' a checked
    property, not prose."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.parallel import exchange as _ex
    from mpi_petsc4py_example_tpu.resilience import faults as _faults
    from mpi_petsc4py_example_tpu.solvers.multisplit import MultisplitSolver

    problems: list[str] = []
    A, b, _x_true, nblocks = _multisplit_problem()
    published: dict[int, list[int]] = {}
    rehomes: list[tuple[int, int]] = []
    orig_pub = _ex.StaleExchange.publish
    orig_repub = _ex.StaleExchange.republish

    def pub(self, block, payload):
        v = orig_pub(self, block, payload)
        if v is not None:
            published.setdefault(block, []).append(v)
        return v

    def repub(self, block, payload, *, version=None):
        orig_repub(self, block, payload, version=version)
        rehomes.append((block, self.version(block)))

    ms = MultisplitSolver(nblocks=nblocks, rtol=RTOL)
    ms.set_operator(A)
    victim = ms._blocks[2].device_id
    _ex.StaleExchange.publish = pub
    _ex.StaleExchange.republish = repub
    try:
        with tps.inject_faults(
                f"device.lost=unavailable:device={victim}:at=5"):
            res = ms.solve(b)
    finally:
        _ex.StaleExchange.publish = orig_pub
        _ex.StaleExchange.republish = orig_repub
        _faults.heal()
    if not res.converged:
        problems.append(f"degraded solve did not converge: {res}")
    if res.blocks_lost < 1:
        problems.append("the armed device.lost never cost a block")
    if not rehomes:
        problems.append("the lost block was never re-homed")
    if min(res.block_steps) <= 0:
        problems.append(f"a block reports zero outer steps: "
                        f"{res.block_steps}")
    for blk, seq in sorted(published.items()):
        if any(b2 <= a for a, b2 in zip(seq, seq[1:])):
            problems.append(
                f"block {blk} published a non-increasing version "
                f"sequence {seq[:12]}... — somebody revisited "
                "iteration 0")
    for blk, frozen in rehomes:
        later = [v for v in published.get(blk, []) if v > frozen]
        if not later and res.converged:
            problems.append(
                f"re-homed block {blk} never published past its frozen "
                f"version {frozen} — re-home did not resume progress")
    rtrue = np.linalg.norm(b - A @ res.x) / np.linalg.norm(b)
    if not rtrue <= RTOL:
        problems.append(f"true relative residual {rtrue:.3e} misses the "
                        "strict tolerance")
    status = "OK" if not problems else "FAIL"
    print(f"[chaos] multisplit-lost: {status} cut={res.cut_version} "
          f"blocks_lost={res.blocks_lost} steps={res.block_steps} "
          f"rehomes={rehomes} true_rres={rtrue:.3e}")
    return [f"multisplit-lost: {p}" for p in problems]


def drill_multisplit_partition() -> list[str]:
    """Exchange partition under the async tier (``--multisplit``): an
    armed ``exchange.put`` drop fault discards a block's publishes — its
    peers see a frozen version and its staleness grows — yet the solve
    may only pay TIME (extra outer steps / resyncs), never correctness:
    strict fp64 parity at a consistent cut."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.resilience import faults as _faults
    from mpi_petsc4py_example_tpu.solvers.multisplit import MultisplitSolver

    problems: list[str] = []
    A, b, _x_true, nblocks = _multisplit_problem()
    ms = MultisplitSolver(nblocks=nblocks, rtol=RTOL)
    ms.set_operator(A)
    try:
        with tps.inject_faults("exchange.put=drop:device=3:at=3:times=6"):
            res = ms.solve(b)
    finally:
        _faults.heal()
    drops = ms._exchange.drops
    if not res.converged:
        problems.append(f"partitioned solve did not converge: {res}")
    if drops < 1:
        problems.append("the armed exchange.put fault never dropped a "
                        "publish")
    rtrue = np.linalg.norm(b - A @ res.x) / np.linalg.norm(b)
    if not rtrue <= RTOL:
        problems.append(f"true relative residual {rtrue:.3e} misses the "
                        "strict tolerance")
    status = "OK" if not problems else "FAIL"
    print(f"[chaos] multisplit-partition: {status} cut={res.cut_version} "
          f"drops={drops} resyncs={res.resyncs} true_rres={rtrue:.3e}")
    return [f"multisplit-partition: {p}" for p in problems]


def _transport_fleet(tps, comm, hosts, **kw):
    """A drill-speed FleetManager: zero batching window, no retry/client
    sleeps (backoff math still runs, the drill just doesn't wait)."""
    from mpi_petsc4py_example_tpu.serving.remote import FleetManager

    return FleetManager(hosts, comm, window=0.0, max_k=4,
                        retry_policy=tps.RetryPolicy(sleep=lambda _d: None),
                        client_sleep=lambda _d: None, **kw)


def drill_transport_loss() -> list[str]:
    """Host loss mid-load (``--transport``): kill the owning replica
    host AFTER a warm solve + lease step cached its elastic checkpoint,
    then submit again — the in-flight future must fail over to the
    survivor, the re-homed solve must RESUME past iteration 0 (the
    FailoverEvent carries the warm-start iteration), and the answer must
    hold strict fp64 residual parity across the failover boundary."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.resilience import faults as _faults

    problems: list[str] = []
    comm = tps.DeviceComm()
    A = poisson2d_csr(10)
    xt = np.random.default_rng(0).random(A.shape[0])
    b = np.asarray(A @ xt)
    mgr = _transport_fleet(tps, comm, 2)
    try:
        mgr.register_operator("a", A, pc_type="jacobi", rtol=RTOL)
        res = mgr.submit("a", b).result(timeout=120)
        r0 = np.linalg.norm(b - A @ res.x) / np.linalg.norm(b)
        if not r0 <= RTOL * 1.05:
            problems.append(f"pre-loss residual {r0:.3e} misses rtol")
        mgr.lease_step()  # pulls the post-solve checkpoint client-side
        owner = mgr.router.owner("a")
        mgr.kill_host(owner)
        res2 = mgr.submit("a", b).result(timeout=120)
        r2 = np.linalg.norm(b - A @ res2.x) / np.linalg.norm(b)
        if not r2 <= RTOL * 1.05:
            problems.append(f"post-loss residual {r2:.3e} misses rtol "
                            "(parity broke across the failover boundary)")
        new_owner = mgr.router.owner("a")
        if new_owner == owner:
            problems.append(f"session never re-homed off the dead host "
                            f"{owner}")
        if not mgr.failovers:
            problems.append("no FailoverEvent was recorded")
        resumed = mgr.failovers[0].resumed_iteration if mgr.failovers \
            else 0
        if resumed <= 0:
            problems.append(f"re-homed solve restarted from iteration 0 "
                            f"(resumed_iteration={resumed}) — the "
                            "checkpoint never shipped")
        status = "OK" if not problems else "FAIL"
        print(f"[chaos] transport-loss: {status} {owner}->{new_owner} "
              f"resumed_iteration={resumed} true_rres={r2:.3e}")
    finally:
        mgr.shutdown(wait=False)
        _faults.heal()
    return [f"transport-loss: {p}" for p in problems]


def drill_transport_duplicate() -> list[str]:
    """Duplicate delivery under retry (``--transport``): a reply
    dropped AFTER the handler ran forces the client to retry the same
    idempotency key (phase A), and an injected request duplication
    delivers one logical call twice (phase B) — in both, the host must
    execute the solve EXACTLY once per logical request (no
    double-solve, no double-resolved future) while serving the
    duplicate from its idempotency cache."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.resilience import faults as _faults

    problems: list[str] = []
    comm = tps.DeviceComm()
    A = poisson2d_csr(10)
    xt = np.random.default_rng(1).random(A.shape[0])
    b = np.asarray(A @ xt)
    mgr = _transport_fleet(tps, comm, 1)
    try:
        mgr.register_operator("a", A, pc_type="jacobi", rtol=RTOL)
        host = mgr.hosts["r0"]
        # phase A: the reply is lost once — the retry must JOIN the
        # already-executed call, not re-run it
        calls0 = host.rpc.stats["calls"]
        with tps.inject_faults("rpc.recv=drop:at=1:times=1"):
            res = mgr.submit("a", b).result(timeout=120)
        ra = np.linalg.norm(b - A @ res.x) / np.linalg.norm(b)
        calls_a = host.rpc.stats["calls"] - calls0
        dups_a = host.rpc.stats["duplicates"]
        if calls_a != 1:
            problems.append(f"lost-reply retry re-executed the handler "
                            f"({calls_a} executions for 1 logical call)")
        if dups_a < 1:
            problems.append("the retried delivery never hit the "
                            "idempotency cache")
        if not ra <= RTOL * 1.05:
            problems.append(f"phase-A residual {ra:.3e} misses rtol")
        # phase B: the request itself is delivered twice
        calls1 = host.rpc.stats["calls"]
        with tps.inject_faults("rpc.send=duplicate:at=1:times=1"):
            res2 = mgr.submit("a", b).result(timeout=120)
        rb = np.linalg.norm(b - A @ res2.x) / np.linalg.norm(b)
        calls_b = host.rpc.stats["calls"] - calls1
        if calls_b != 1:
            problems.append(f"duplicated request double-solved "
                            f"({calls_b} executions for 1 logical call)")
        if not rb <= RTOL * 1.05:
            problems.append(f"phase-B residual {rb:.3e} misses rtol")
        st = mgr.stubs["r0"].stats()
        if st["requests"] != 2:
            problems.append(f"server saw {st['requests']} requests for "
                            "2 logical solves — duplicates leaked "
                            "through to the solve queue")
        status = "OK" if not problems else "FAIL"
        print(f"[chaos] transport-duplicate: {status} "
              f"executions={calls_a}+{calls_b} "
              f"cache_hits={host.rpc.stats['duplicates']} "
              f"server_requests={st['requests']}")
    finally:
        mgr.shutdown(wait=False)
        _faults.heal()
    return [f"transport-duplicate: {p}" for p in problems]


def drill_transport_partition() -> list[str]:
    """Partition during live migration (``--transport``): a sticky
    partition of the migration DESTINATION makes the move fail after
    the dst may already hold a registered copy. The router's placement
    must stay truthful (src still owns and serves at parity), and once
    the partition heals, ``reconcile()`` must converge the fleet to a
    SINGLE truthful placement table — the orphaned dst copy is
    unregistered, never split-brained."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    from mpi_petsc4py_example_tpu.resilience import faults as _faults
    from mpi_petsc4py_example_tpu.serving.transport import TransportError

    problems: list[str] = []
    comm = tps.DeviceComm()
    A = poisson2d_csr(10)
    xt = np.random.default_rng(2).random(A.shape[0])
    b = np.asarray(A @ xt)
    mgr = _transport_fleet(tps, comm, 2)
    try:
        mgr.register_operator("p", A, pc_type="jacobi", rtol=RTOL)
        src = mgr.router.owner("p")
        dst = next(n for n in mgr.stubs if n != src)
        dst_idx = int(dst[1:])
        migrate_failed = False
        with tps.inject_faults(
                f"rpc.recv=partition:device={dst_idx}:times=*"):
            try:
                mgr.router.migrate("p", dst)
            except (TransportError, tps.DeadlineExceededError,
                    RuntimeError):
                migrate_failed = True
            if not migrate_failed:
                problems.append("migration across a partitioned "
                                "destination reported success")
            if mgr.router.owner("p") != src:
                problems.append(f"placement lied during the partition: "
                                f"owner={mgr.router.owner('p')} != {src}")
            res = mgr.submit("p", b).result(timeout=120)
            rr = np.linalg.norm(b - A @ res.x) / np.linalg.norm(b)
            if not rr <= RTOL * 1.05:
                problems.append(f"during-partition residual {rr:.3e} "
                                "misses rtol")
        # partition healed: the dst may hold an orphaned epoch-stamped
        # copy — reconcile must remove it and keep ONE truthful owner
        rep = mgr.reconcile()
        res_src = mgr.stubs[src].client.call("resident", {}, deadline=10.0)
        res_dst = mgr.stubs[dst].client.call("resident", {}, deadline=10.0)
        if "p" not in res_src:
            problems.append(f"the authoritative copy vanished from {src}")
        if "p" in res_dst:
            problems.append(f"split brain: {dst} still holds 'p' after "
                            "reconcile")
        if mgr.router.owner("p") != src:
            problems.append(f"reconcile re-homed away from the healthy "
                            f"owner: {mgr.router.owner('p')}")
        res3 = mgr.submit("p", b).result(timeout=120)
        r3 = np.linalg.norm(b - A @ res3.x) / np.linalg.norm(b)
        if not r3 <= RTOL * 1.05:
            problems.append(f"post-reconcile residual {r3:.3e} misses "
                            "rtol")
        status = "OK" if not problems else "FAIL"
        print(f"[chaos] transport-partition: {status} src={src} dst={dst} "
              f"orphans_removed={rep['orphans_removed']} "
              f"true_rres={r3:.3e}")
    finally:
        mgr.shutdown(wait=False)
        _faults.heal()
    return [f"transport-partition: {p}" for p in problems]


def validate_trace(trace_path: str, evict: bool) -> list[str]:
    """Structural validation of the exported Perfetto trace + flight
    dump — the CI telemetry job's schema gate."""
    import json

    from mpi_petsc4py_example_tpu import telemetry

    problems: list[str] = []
    with open(trace_path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents", [])
    if not evs:
        return [f"trace {trace_path}: empty traceEvents"]
    names = set()
    for e in evs:
        missing = [k for k in ("name", "ph", "pid") if k not in e]
        if e.get("ph") == "X":
            missing += [k for k in ("ts", "dur", "tid") if k not in e]
            names.add(e["name"])
        if missing:
            problems.append(f"trace event {e.get('name')!r} missing "
                            f"key(s) {missing}")
            break
    if evict:
        # the acceptance drill: the eviction's retry -> shrink chain
        # must be in the trace, shrink carrying the resumed iteration
        for want in ("resilient.solve", "resilient.shrink", "ksp.solve"):
            if want not in names:
                problems.append(f"trace has no {want!r} span")
        shrinks = [e for e in evs if e.get("ph") == "X"
                   and e["name"] == "resilient.shrink"]
        if not any(int(e.get("args", {}).get("resumed_iteration", 0)) > 0
                   for e in shrinks):
            problems.append("no resilient.shrink span carries a positive "
                            "resumed_iteration attribute")
        # the chain must also survive as a TREE in the flight ring: a
        # resilient.solve root whose descendants include the shrink
        def has_shrink(tree):
            return (tree["name"] == "resilient.shrink"
                    or any(has_shrink(c) for c in tree["children"]))
        roots = telemetry.flight_recorder.spans()
        if not any(t["name"] == "resilient.solve" and has_shrink(t)
                   for t in roots):
            problems.append("flight ring holds no resilient.solve tree "
                            "containing the shrink span")
    flight_path = trace_path + ".flight.json"
    with open(flight_path) as f:
        dump = json.load(f)
    if not dump.get("entries"):
        problems.append(f"flight dump {flight_path} is empty")
    if evict and not any(e.get("type") == "event"
                         and e.get("kind") == "fault"
                         and e["data"].get("point") == "device.lost"
                         for e in dump.get("entries", [])):
        problems.append("flight dump records no device.lost fault event")
    return [f"trace: {p}" for p in problems]


def main() -> int:
    import contextlib

    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu import telemetry

    failures: list[str] = []
    argv = sys.argv[1:]
    trace_out = None
    if "--trace-out" in argv:
        i = argv.index("--trace-out")
        if i + 1 >= len(argv):
            print("--trace-out needs a path", file=sys.stderr)
            return 2
        trace_out = argv[i + 1]
        telemetry.enable()
    env_spec = os.environ.get("TPU_SOLVE_FAULTS", "").strip()
    if "--fleet" in sys.argv[1:]:
        # ISSUE 13 acceptance: loss -> shrink -> heal -> RE-GROW end to
        # end — the solve resumes past iteration 0 on the re-grown
        # mesh, every mixed-QoS future resolves, and post-heal capacity
        # returns to the provisioned mesh
        failures += drill_fleet_regrow()
        failures += drill_fleet_serving()
        what = "fleet loss/shrink/heal/re-grow"
    elif "--evict" in sys.argv[1:]:
        # ISSUE 8 acceptance: permanent device loss mid-solve AND
        # mid-serving-load must recover onto a strictly smaller mesh
        failures += drill_evict_solve()
        failures += drill_evict_serving()
        what = "device-eviction"
    elif "--megasolve" in sys.argv[1:]:
        # ISSUE 12 acceptance: a bitflip inside the FUSED whole-solve
        # loop must detect -> rollback -> re-enter at one dispatch per
        # attempt
        failures += drill_megasolve()
        what = "megasolve fused-loop corruption"
    elif "--multisplit" in sys.argv[1:]:
        # ISSUE 17 acceptance: the async tier must absorb a sticky slow
        # device as bounded staleness, degrade a mid-solve device.lost
        # to ONE stale block (survivors provably never revisit
        # iteration 0), and pay an exchange partition only in staleness
        failures += drill_multisplit_jitter()
        failures += drill_multisplit_lost()
        failures += drill_multisplit_partition()
        what = "asynchronous-multisplit staleness/loss"
    elif "--transport" in sys.argv[1:]:
        # ISSUE 20 acceptance: host loss mid-load must resolve every
        # pending future with the re-homed solve resuming past
        # iteration 0; injected duplicate delivery must never
        # double-solve or double-resolve; a healed partition must
        # reconcile to a single truthful placement table
        failures += drill_transport_loss()
        failures += drill_transport_duplicate()
        failures += drill_transport_partition()
        what = "fleet-transport loss/duplicate/partition"
    elif "--persistent" in sys.argv[1:]:
        # ISSUE 18 acceptance: a bitflip across a fully-staged
        # persistent launch must resolve every slot with no silently-
        # wrong answer, and a mid-launch device loss must shrink,
        # resume past iteration 0, and rebuild the resident program
        failures += drill_persistent_bitflip()
        failures += drill_persistent_lost()
        what = "persistent-serving corruption/loss"
    elif "--sstep" in sys.argv[1:]:
        # ISSUE 15 acceptance: a bitflip inside an s-block must detect
        # -> rollback to the verified carry -> re-enter, and the
        # ill-conditioned-basis demotion chain must land on classic CG
        failures += drill_sstep()
        what = "s-step block corruption + demotion"
    elif env_spec:
        # env-armed: the plan is already active from the environment
        failures += drill(f"env:{env_spec}", contextlib.nullcontext())
        what = "silent-corruption"
    else:
        for spec in BUILTIN_SPECS:
            failures += drill(spec, tps.inject_faults(spec))
        what = "silent-corruption"
    if trace_out:
        telemetry.export_trace(trace_out)
        telemetry.flight_recorder.dump(trace_out + ".flight.json",
                                       reason="chaos smoke")
        failures += validate_trace(trace_out, "--evict" in sys.argv[1:])
        print(f"[chaos] trace exported to {trace_out} "
              f"(+ {trace_out}.flight.json)")
    if failures:
        print("[chaos] FAILURES:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"[chaos] all {what} drills recovered and verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
