#!/usr/bin/env python
"""Chaos smoke: silent-data-corruption drills for CI (ISSUE 5 satellite).

Runs a CG solve under silent-corruption fault specs and asserts the full
detection -> rollback -> recovery -> verification chain:

* a detector fired (ABFT checksum / drift gate / sentinel — the
  recovery trail carries its name);
* the recovered answer's fp64 TRUE relative residual meets rtol;
* the iterate matches the manufactured solution.

Exit status is NONZERO if corruption goes undetected or the recovered
answer is wrong — the CI contract that silent corruption cannot
silently regress.

Two modes:

* ``TPU_SOLVE_FAULTS`` set in the environment: ONE drill under exactly
  that spec (the env-activation route, like the crash smoke steps);
* unset: the builtin sweep over every silent fault kind at every
  injectable point (spmv.result / pc.apply / comm.psum), via
  ``inject_faults``.
"""

from __future__ import annotations

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RTOL = 1e-10

#: the builtin sweep: every silent kind at every injectable point
#: (at=2 targets the loop apply; times=1 lets the retry re-trace clean)
BUILTIN_SPECS = (
    "spmv.result=bitflip:at=2:times=1",
    "spmv.result=scale:mag=1e-3:at=2:times=1",
    "pc.apply=bitflip:at=2:times=1",
    "pc.apply=scale:mag=1e-2:at=2:times=1",
    "comm.psum=corrupt:times=1:at=3",
)


def drill(label: str, ctx) -> list[str]:
    """One corruption drill; returns a list of failure descriptions."""
    import mpi_petsc4py_example_tpu as tps
    from mpi_petsc4py_example_tpu.models import poisson2d_csr

    comm = tps.DeviceComm()
    A = poisson2d_csr(12)
    M = tps.Mat.from_scipy(comm, A)
    ksp = tps.KSP().create(comm)
    ksp.set_operators(M)
    ksp.set_type("cg")
    ksp.get_pc().set_type("jacobi")
    ksp.set_tolerances(rtol=RTOL)
    ksp.abft = True
    ksp.residual_replacement = 10
    x_true = np.random.default_rng(0).random(A.shape[0])
    b = A @ x_true
    x, bv = M.get_vecs()
    bv.set_global(b)

    problems: list[str] = []
    with ctx:
        res = tps.resilient_solve(
            ksp, bv, x, tps.RetryPolicy(sleep=lambda _d: None))
    detectors = [e.detector for e in res.recovery_events
                 if e.kind == "fault" and e.detector]
    if not detectors:
        problems.append("corruption went UNDETECTED (no detector event)")
    if not res.converged:
        problems.append(f"recovered solve did not converge: {res}")
    if not any(e.kind == "verify" for e in res.recovery_events):
        problems.append("no post-recovery true-residual verification ran")
    rtrue = (np.linalg.norm(b - A @ x.to_numpy())
             / np.linalg.norm(b))
    if not rtrue <= RTOL * 1.05:
        problems.append(f"true relative residual {rtrue:.3e} misses rtol")
    if not np.allclose(x.to_numpy(), x_true, atol=1e-7):
        problems.append("recovered iterate differs from the manufactured "
                        "solution")
    status = "OK" if not problems else "FAIL"
    print(f"[chaos] {label}: {status} detectors={detectors} "
          f"attempts={res.attempts} true_rres={rtrue:.3e}")
    return [f"{label}: {p}" for p in problems]


def main() -> int:
    import contextlib

    import mpi_petsc4py_example_tpu as tps

    failures: list[str] = []
    env_spec = os.environ.get("TPU_SOLVE_FAULTS", "").strip()
    if env_spec:
        # env-armed: the plan is already active from the environment
        failures += drill(f"env:{env_spec}", contextlib.nullcontext())
    else:
        for spec in BUILTIN_SPECS:
            failures += drill(spec, tps.inject_faults(spec))
    if failures:
        print("[chaos] FAILURES:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("[chaos] all silent-corruption drills recovered and verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
