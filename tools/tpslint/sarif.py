"""SARIF 2.1.0 emitter — tpslint findings as GitHub code-scanning input.

``tpslint --sarif out.sarif ...`` serializes an
:class:`~tools.tpslint.engine.AnalysisResult` into a Static Analysis
Results Interchange Format log (OASIS SARIF 2.1.0), the format GitHub's
``codeql-action/upload-sarif`` turns into inline PR annotations.  Kept
deliberately minimal — one run, one tool.driver, one result per
finding — and strictly schema-shaped:

* ``version``/``$schema`` pin 2.1.0;
* every emitted ``ruleId`` has a matching ``tool.driver.rules`` entry
  (GitHub requires the reporting descriptor to resolve);
* levels map severity tiers: error-tier findings, bad suppressions and
  parse errors -> ``error``; warn-tier (TPS011-style advisories) ->
  ``warning``; stale suppressions -> ``note`` (informational — they
  only fail ``--strict``);
* locations use 1-based lines AND columns (SARIF convention; tpslint
  columns are 0-based ast offsets) and forward-slash relative URIs.

``tests/test_tpslint.py`` validates the output against the SARIF 2.1.0
schema's structural requirements.
"""

from __future__ import annotations

import json
import os

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

#: pseudo-rules the engine emits outside the registered rule set
_PSEUDO_RULES = {
    "TPS000": ("bad-suppression",
               "a `# tpslint: disable=` comment without the required "
               "justification"),
    "TPS-STALE": ("stale-suppression",
                  "a justified suppression that no longer fires "
                  "(fails --strict)"),
    "TPS-PARSE": ("parse-error", "the file does not parse"),
    "TPS-READ": ("read-error", "the file cannot be read"),
}


def _uri(path: str, base_dir: str | None) -> str:
    if base_dir:
        try:
            rel = os.path.relpath(path, base_dir)
            if not rel.startswith(".."):
                path = rel
        except ValueError:
            pass
    return path.replace(os.sep, "/")


def _result(finding, level: str, base_dir) -> dict:
    return {
        "ruleId": finding.rule,
        "level": level,
        "message": {"text": finding.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": _uri(finding.path, base_dir)},
                "region": {
                    "startLine": max(1, finding.line),
                    "startColumn": finding.col + 1,
                },
            },
        }],
    }


def to_sarif(result, rules, base_dir: str | None = None) -> dict:
    """Serialize an AnalysisResult (plus the rule registry metadata) to a
    SARIF 2.1.0 log dict."""
    results = []
    for f in result.errors:
        results.append(_result(f, "error", base_dir))
    for f in result.findings:
        results.append(_result(f, "error", base_dir))
    for f in result.bad_suppressions:
        results.append(_result(f, "error", base_dir))
    for f in result.warnings:
        results.append(_result(f, "warning", base_dir))
    for s in result.unused_suppressions:
        results.append({
            "ruleId": "TPS-STALE",
            "level": "note",
            "message": {"text": (f"unused suppression of "
                                 f"{', '.join(s.rules)} (nothing fires on "
                                 "the guarded line)")},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(s.path, base_dir)},
                    "region": {"startLine": max(1, s.line),
                               "startColumn": 1},
                },
            }],
        })

    driver_rules = []
    for rid, rule in sorted(rules.items()):
        driver_rules.append({
            "id": rid,
            "name": rule.name,
            "shortDescription": {"text": rule.description},
            "defaultConfiguration": {
                "level": "warning" if rule.severity == "warn" else "error"},
        })
    emitted = {r["ruleId"] for r in results}
    for rid, (name, desc) in _PSEUDO_RULES.items():
        if rid in emitted:
            driver_rules.append({
                "id": rid,
                "name": name,
                "shortDescription": {"text": desc},
                "defaultConfiguration": {
                    "level": "note" if rid == "TPS-STALE" else "error"},
            })

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "tpslint",
                    "informationUri":
                        "https://github.com/tpu-sparse-solve",
                    "rules": driver_rules,
                },
            },
            "results": results,
        }],
    }


def write_sarif(path: str, result, rules, base_dir: str | None = None):
    """Write the SARIF log atomically (CI uploads must never see a
    truncated file)."""
    doc = to_sarif(result, rules, base_dir=base_dir)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)
