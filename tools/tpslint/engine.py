"""Analysis driver: index (phase 1), run rules (phase 2), suppressions.

Round 9: linting is two-phase.  Phase 1 parses EVERY file under the
given paths into a :class:`~tools.tpslint.context.ModuleAnalysis` and
builds the project-wide :class:`~tools.tpslint.program.ProgramIndex`
(module/symbol table + call graph + dataflow summaries).  Phase 2 runs
the rules per module with ``module.program`` pointing at the index, so
interprocedural rules (TPS008 host-sync reachability, TPS013 donation
safety) see the whole program while findings stay anchored to one file.

``report_files`` decouples the two scopes: the index always covers all
``paths``, but findings are reported only for the listed files — the
``tpslint --changed-files`` PR-lint mode, where a cross-file finding in
an unchanged file must not fail a PR that didn't touch it, yet the
changed files are still analyzed against the FULL call graph.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from .context import ModuleAnalysis
from .findings import (BAD_SUPPRESSION, Finding, Suppression,
                       parse_suppressions)
from .program import ProgramIndex
from .rules import all_rules


@dataclass
class AnalysisResult:
    """Outcome of linting one or more files."""

    findings: list = field(default_factory=list)       # unsuppressed errors
    warnings: list = field(default_factory=list)       # unsuppressed warn-tier
    suppressed: list = field(default_factory=list)     # (Finding, Suppression)
    bad_suppressions: list = field(default_factory=list)   # Finding (TPS000)
    unused_suppressions: list = field(default_factory=list)  # Suppression
    errors: list = field(default_factory=list)         # Finding (parse)
    files_linted: int = 0
    #: the phase-1 ProgramIndex (analyze_paths/analyze_source fill it in)
    index: ProgramIndex | None = None

    def merge(self, other: "AnalysisResult"):
        self.findings.extend(other.findings)
        self.warnings.extend(other.warnings)
        self.suppressed.extend(other.suppressed)
        self.bad_suppressions.extend(other.bad_suppressions)
        self.unused_suppressions.extend(other.unused_suppressions)
        self.errors.extend(other.errors)
        self.files_linted += other.files_linted

    def exit_code(self, strict: bool = False,
                  warn_budget: int | None = None) -> int:
        """Errors always fail; warn-tier findings fail only past an
        explicit ``--warn-budget`` (None = advisory only, never fails) —
        the CI shape for rules like TPS011 where existing call sites are
        acceptable but silent accumulation is not."""
        if self.findings or self.bad_suppressions or self.errors:
            return 1
        if strict and self.unused_suppressions:
            return 1
        if warn_budget is not None and len(self.warnings) > warn_budget:
            return 1
        return 0


def _lint_module(module: ModuleAnalysis, rules) -> AnalysisResult:
    """Phase 2 for one already-parsed module: run rules, apply
    suppressions."""
    result = AnalysisResult()
    path = module.path
    raw = []
    for rule in rules.values():
        for f in rule.check(module):
            raw.append(Finding(rule=f.rule, message=f.message,
                               line=f.line, col=f.col, path=path,
                               severity=f.severity))
    raw.sort(key=lambda f: (f.line, f.col, f.rule))

    suppressions = parse_suppressions(module.source)
    for s in suppressions:
        s.path = path

    # findings anchor at a statement's FIRST line; a trailing suppression on
    # a continuation line of a multi-line statement must still guard it
    stmt_spans = [(n.lineno, n.end_lineno) for n in ast.walk(module.tree)
                  if isinstance(n, ast.stmt) and n.end_lineno is not None]

    def _statement_start(line: int):
        spans = [s0 for s0, s1 in stmt_spans if s0 <= line <= s1]
        return max(spans) if spans else None

    guard = {}      # line -> [Suppression]
    for s in suppressions:
        if not s.standalone:
            start = _statement_start(s.line)
            if start is not None and start not in s.guarded_lines:
                s.guarded_lines = s.guarded_lines + (start,)
    for s in suppressions:
        if not s.justification:
            result.bad_suppressions.append(Finding(
                rule=BAD_SUPPRESSION,
                message=(f"suppression of {', '.join(s.rules)} carries no "
                         "justification — `# tpslint: disable=TPSxxx — "
                         "why the code is right` is required"),
                line=s.line, col=0, path=path))
            # an unjustified suppression still suppresses nothing
            continue
        for line in s.guarded_lines:
            guard.setdefault(line, []).append(s)

    for f in raw:
        sup = next((s for s in guard.get(f.line, ()) if f.rule in s.rules),
                   None)
        if sup is not None:
            sup.used = True
            result.suppressed.append((f, sup))
        elif f.severity == "warn":
            result.warnings.append(f)
        else:
            result.findings.append(f)

    # a suppression can only be "unused" with respect to rules that actually
    # ran — under --select, suppressions of deselected rules are not stale
    active = set(rules)
    result.unused_suppressions.extend(
        s for s in suppressions
        if s.justification and not s.used and active.intersection(s.rules))
    return result


def _selected_rules(select):
    rules = all_rules()
    if select is not None:
        wanted = set(select)
        rules = {rid: r for rid, r in rules.items() if rid in wanted}
    return rules


def analyze_source(source: str, path: str = "<string>",
                   select=None, index: ProgramIndex | None = None
                   ) -> AnalysisResult:
    """Lint one module's source.  ``select`` optionally restricts to an
    iterable of rule ids.  Without a caller-provided ``index`` the module
    gets a single-file program index — interprocedural rules still work
    within the module."""
    result = AnalysisResult()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        result.errors.append(Finding(
            rule="TPS-PARSE", message=f"syntax error: {e.msg}",
            line=e.lineno or 1, col=(e.offset or 1) - 1, path=path))
        return result

    module = ModuleAnalysis(tree, source, path)
    if index is None:
        index = ProgramIndex([module])
    else:
        index.add_module(module)
    result.index = index
    lint = _lint_module(module, _selected_rules(select))
    lint.index = index
    lint.files_linted = 0
    result.merge(lint)
    return result


def iter_python_files(paths):
    """Expand files/directories into .py files, skipping hidden dirs and
    __pycache__."""
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if not d.startswith(".") and d != "__pycache__")
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def build_index(paths) -> tuple:
    """Phase 1: parse every .py file under ``paths`` into a
    ProgramIndex.  Returns ``(index, read_or_parse_error_findings)`` —
    unreadable/unparsable files are reported, never silently skipped."""
    index = ProgramIndex([])
    errors = []
    for fname in iter_python_files(paths):
        try:
            with open(fname, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            errors.append(Finding(
                rule="TPS-READ", message=f"cannot read: {e}", line=1, col=0,
                path=fname))
            continue
        try:
            tree = ast.parse(source, filename=fname)
        except SyntaxError as e:
            errors.append(Finding(
                rule="TPS-PARSE", message=f"syntax error: {e.msg}",
                line=e.lineno or 1, col=(e.offset or 1) - 1, path=fname))
            continue
        index.add_module(ModuleAnalysis(tree, source, fname))
    return index, errors


def analyze_paths(paths, select=None, report_files=None,
                  index: ProgramIndex | None = None) -> AnalysisResult:
    """Lint every .py file under ``paths`` (files or directories).

    ``report_files`` (an iterable of files/directories) restricts which
    files' findings are REPORTED; the program index still covers all of
    ``paths`` so cross-file analysis stays whole-program.  ``index``
    short-circuits phase 1 with a prebuilt/cached ProgramIndex.
    """
    total = AnalysisResult()
    if index is None:
        index, errors = build_index(paths)
        total.errors.extend(errors)
    total.index = index

    if report_files is None:
        report = None
    else:
        report = {os.path.normpath(f)
                  for f in iter_python_files(report_files)}
        total.errors = [e for e in total.errors
                        if os.path.normpath(e.path) in report]

    rules = _selected_rules(select)
    for path, entry in sorted(index.modules.items()):
        if report is not None and path not in report:
            continue
        total.merge(_lint_module(entry.analysis, rules))
        total.files_linted += 1
    total.index = index
    return total
