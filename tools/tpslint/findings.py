"""Finding and suppression primitives shared by the engine and the rules."""

from __future__ import annotations

import re
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    """One diagnostic: a rule fired at a source location.

    ``severity``: ``"error"`` (the default — fails the lint) or ``"warn"``
    (advisory tier, round 6: counted against the CI ``--warn-budget`` but
    never a failure by itself — the tier advisory rules like TPS011 need,
    ROADMAP deferred item)."""

    rule: str          # "TPS001"
    message: str       # human-readable, one line
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    path: str = ""     # filled in by the engine
    severity: str = "error"

    def format(self) -> str:
        tag = " warning:" if self.severity == "warn" else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}{tag} "
                f"{self.message}")


# ``tpslint: disable=TPSnnn`` or ``tpslint: disable=TPSnnn,TPSmmm — why``.
# The justification is REQUIRED: a suppression is a claim that a human looked
# at the finding and decided the code is right — the claim must say why, or
# the next reader cannot audit it.
_SUPPRESS_RE = re.compile(
    r"#\s*tpslint:\s*disable=([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)(.*)$")
# Leading separators between the rule list and the justification text.
_SEP_RE = re.compile(r"^[\s—–:,-]+")

#: Pseudo-rule id for malformed suppressions (never suppressible itself).
BAD_SUPPRESSION = "TPS000"


@dataclass
class Suppression:
    """A parsed ``# tpslint: disable=`` comment."""

    line: int                 # line the comment sits on (1-based)
    rules: tuple              # ("TPS001", "TPS005")
    justification: str        # may be "" — that is an error
    standalone: bool          # comment is the whole line -> guards next code
    guarded_lines: tuple = () # source lines this suppression applies to
    used: bool = field(default=False, compare=False)
    path: str = field(default="", compare=False)


def _comment_tokens(source: str):
    """(lineno, col, text) for every real COMMENT token — tokenizing (not
    line-regexing) so a docstring that *documents* the suppression syntax
    is never parsed as a live suppression."""
    import io
    import tokenize
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.start[1], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # the engine reports the parse error separately; no comments here
        return


def parse_suppressions(source: str):
    """Extract all suppression comments from ``source``.

    A trailing comment guards its own line; a standalone comment line (or
    block of comment lines — justifications often wrap) guards the next
    non-blank, non-comment line below it.
    """
    lines = source.splitlines()
    out = []
    for lineno, col, text in _comment_tokens(source):
        m = _SUPPRESS_RE.search(text)
        if m is None:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(","))
        justification = _SEP_RE.sub("", m.group(2).strip()).strip()
        standalone = lines[lineno - 1][:col].strip() == ""
        if standalone:
            guarded = ()
            for nxt in range(lineno, len(lines)):
                stripped = lines[nxt].strip()
                if stripped and not stripped.startswith("#"):
                    guarded = (nxt + 1,)
                    break
        else:
            guarded = (lineno,)
        out.append(Suppression(line=lineno, rules=rules,
                               justification=justification,
                               standalone=standalone,
                               guarded_lines=guarded))
    return out
