"""Program-index cache — skip phase-1 re-parsing when the tree is
unchanged.

CI runs tpslint several times per workflow (the full ``--strict`` run
plus the per-subsystem ``--strict <subdir>`` steps of the serving /
multichip / resilience jobs).  With the round-9 two-phase engine each
run would re-parse the whole tree just to rebuild the same program
index.  ``tpslint --index-cache PATH`` pickles the index keyed on a
source-tree hash: a hit loads the parsed modules (and the phase-1
read/parse error findings) instead of re-parsing; any content change,
tpslint-source change, or Python version change misses and rebuilds.

Cache failures are NEVER lint failures — a corrupt/unreadable/stale
blob silently falls back to a fresh build (and rewrites the cache).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import sys

#: bump when the pickled shape changes incompatibly
FORMAT_VERSION = 1


def _tpslint_source_digest() -> str:
    """Hash of the tpslint package's own sources — a rule or engine
    change must invalidate cached indexes built by the old code."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for root, dirs, files in os.walk(pkg):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            fp = os.path.join(root, name)
            h.update(name.encode())
            with open(fp, "rb") as fh:
                h.update(hashlib.sha256(fh.read()).digest())
    return h.hexdigest()


def tree_hash(paths) -> str:
    """Content hash over every .py file the index would cover, plus the
    tpslint source digest and the interpreter version (ast pickles are
    not portable across minor versions)."""
    from .engine import iter_python_files
    h = hashlib.sha256()
    h.update(f"fmt{FORMAT_VERSION};py{sys.version_info[:2]}".encode())
    h.update(_tpslint_source_digest().encode())
    for fname in sorted(iter_python_files(paths)):
        h.update(os.path.normpath(fname).encode())
        h.update(b"\0")
        try:
            with open(fname, "rb") as fh:
                h.update(hashlib.sha256(fh.read()).digest())
        except OSError:
            h.update(b"<unreadable>")
    return h.hexdigest()


def load_index(cache_path: str, key: str):
    """``(index, phase1_errors)`` on a hit, None on any miss/failure."""
    try:
        with open(cache_path, "rb") as fh:
            blob = pickle.load(fh)
        if blob.get("key") != key:
            return None
        return blob["index"], blob["errors"]
    # tpslint: disable=TPS005 — unpickling an arbitrary stale blob can
    # raise nearly anything (Unpickling/Attribute/Import/Memory errors);
    # every cache failure is by contract a silent miss, never a lint
    # failure, and nothing is swallowed that a rebuild doesn't redo
    except Exception:       # noqa: BLE001
        return None


def save_index(cache_path: str, key: str, index, errors):
    """Atomic best-effort write; failures are silent (the lint already
    has its result — caching is an optimization, never a gate)."""
    tmp = f"{cache_path}.tmp.{os.getpid()}"
    try:
        parent = os.path.dirname(os.path.abspath(cache_path))
        os.makedirs(parent, exist_ok=True)
        limit = sys.getrecursionlimit()
        sys.setrecursionlimit(max(limit, 100000))
        try:
            with open(tmp, "wb") as fh:
                pickle.dump({"key": key, "index": index, "errors": errors},
                            fh, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, cache_path)
        finally:
            sys.setrecursionlimit(limit)
    # tpslint: disable=TPS005 — pickling deep ASTs can raise Recursion/
    # Pickling/OS errors; the cache is an optimization, the lint result
    # is already computed, so every failure degrades to "no cache"
    except Exception:       # noqa: BLE001
        try:
            os.unlink(tmp)
        except OSError:
            pass
