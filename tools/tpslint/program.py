"""Project-wide program index: module/symbol table, call graph, dataflow.

Round 9 grows tpslint from a per-file, per-function linter into a
project-wide analysis.  The whole-program invariants the codebase rests
on — no host sync reachable from inside a jitted program (TPS008), no
read of a donated buffer after dispatch (TPS013), grid-spec objects
consistent wherever they are constructed (TPS010) — cannot be seen one
function body at a time: the analyzer has to follow calls.

Three layers, all stdlib-``ast`` only (the TPS012 constraint — tpslint
never imports framework packages, so it lints files that need a TPU
backend to even import):

* **module/symbol table** — every analyzed file becomes a
  :class:`ModuleEntry` carrying its :class:`~tools.tpslint.context.
  ModuleAnalysis`, a dotted-name key derived from its path, an import
  table (absolute and relative imports resolved against the indexed
  file set), and a symbol table of top-level functions and class
  methods as :class:`FunctionRecord` objects;

* **call graph** — :meth:`ProgramIndex.resolve_call` resolves a call
  site to a :class:`FunctionRecord`: local names through the enclosing
  scopes, ``self.method()`` through the enclosing class,
  ``ClassName.method`` / ``module.func`` / from-imported names through
  the import table, across files.  Unresolvable targets (function-valued
  parameters, dynamic attributes) stay ``None`` — the analysis is
  conservative but never guesses;

* **dataflow** — a small intraprocedural lattice: per-function
  reaching-definitions over locals (:func:`local_bindings`,
  :meth:`ProgramIndex.resolve_local_value`) and *value provenance*
  ("this name holds a donated operand / a grid-spec object / a traced
  array").  TPS008 additionally computes per-parameter *sync
  summaries* — which parameters of a function flow (transitively,
  through the call graph) into a host-syncing operation — so a jitted
  caller passing a traced value into a helper three calls away from the
  ``float()`` gets the full chain in the finding message.

The index is built ONCE per run (engine phase 1) and handed to every
rule via ``module.program`` (phase 2); it pickles, so CI can cache it
keyed on the source-tree hash (``tpslint --index-cache``).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from .context import (FUNCTION_NODES, ModuleAnalysis, qualifier_chain,
                      terminal_name)

#: Host-syncing operations TPS008 summarizes (superset of TPS001's sets:
#: the interprocedural pass also covers ``jax.device_get``, which a
#: helper legitimately uses on host paths but must never reach traced).
SYNC_SCALAR_CASTS = {"float", "int", "bool", "complex"}
SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host_async"}
SYNC_JAX_CALLS = {"device_get"}


def module_parts(path: str) -> tuple:
    """Dotted-module parts derived from a file path.

    ``mpi_petsc4py_example_tpu/solvers/krylov.py`` ->
    ``("mpi_petsc4py_example_tpu", "solvers", "krylov")``;
    ``pkg/__init__.py`` -> ``("pkg",)``.  Path segments that are not
    identifiers (and everything before them) are dropped, so absolute
    paths key on their importable suffix.
    """
    norm = os.path.normpath(path)
    if norm.endswith(".py"):
        norm = norm[:-3]
    raw = [p for p in norm.split(os.sep) if p not in ("", ".")]
    if raw and raw[-1] == "__init__":
        raw = raw[:-1]
    parts: list = []
    for seg in reversed(raw):
        if not seg.isidentifier():
            break
        parts.append(seg)
    parts.reverse()
    return tuple(parts)


@dataclass
class FunctionRecord:
    """One function def in the symbol table."""

    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    entry: "ModuleEntry"
    qualname: str                  # "func" or "Class.method"
    is_method: bool = False

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def path(self) -> str:
        return self.entry.path

    def param_names(self) -> list:
        a = self.node.args
        names = [p.arg for p in (a.posonlyargs + a.args)]
        return names

    def positional_param(self, index: int):
        """Parameter name receiving positional argument ``index`` at a
        call site (``self`` already skipped for methods)."""
        params = self.param_names()
        if self.is_method and params:
            params = params[1:]
        if 0 <= index < len(params):
            return params[index]
        return None

    def keyword_param(self, name: str):
        a = self.node.args
        allnames = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        return name if name in allnames else None

    def is_traced(self) -> bool:
        """The def is itself a traced context in its module — TPS001's
        domain, so the interprocedural pass skips into it."""
        return self.node in self.entry.analysis._trace_reasons

    def is_host_target(self) -> bool:
        return self.node in self.entry.analysis._host_marked


@dataclass
class ModuleEntry:
    """One analyzed file in the program index."""

    path: str
    parts: tuple                   # dotted-module parts
    analysis: ModuleAnalysis
    #: top-level name -> FunctionRecord, plus "Class.method" entries
    symbols: dict = field(default_factory=dict)
    #: local import alias -> (module_parts, symbol_or_None)
    imports: dict = field(default_factory=dict)

    def collect(self):
        tree = self.analysis.tree
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.symbols[stmt.name] = FunctionRecord(
                    stmt, self, stmt.name)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        is_static = any(
                            terminal_name(d) == "staticmethod"
                            for d in sub.decorator_list)
                        rec = FunctionRecord(
                            sub, self, f"{stmt.name}.{sub.name}",
                            is_method=not is_static)
                        self.symbols[f"{stmt.name}.{sub.name}"] = rec
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    target = (tuple(a.name.split(".")) if a.asname
                              else (a.name.split(".")[0],))
                    self.imports[alias] = (target, None)
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(node)
                if base is None:
                    continue
                for a in node.names:
                    alias = a.asname or a.name
                    self.imports[alias] = (base, a.name)
        return self

    def _import_base(self, node: ast.ImportFrom):
        """Absolute module parts of an ImportFrom's source module, with
        relative imports resolved against this module's own parts."""
        mod = tuple(node.module.split(".")) if node.module else ()
        if not node.level:
            return mod
        # level=1 strips the module segment, each extra level one package
        if node.level > len(self.parts):
            return None
        return self.parts[:len(self.parts) - node.level] + mod


class ProgramIndex:
    """The project-wide analysis: symbol table + call graph + summaries."""

    def __init__(self, modules):
        #: normalized path -> ModuleEntry
        self.modules = {}
        #: dotted parts -> [ModuleEntry] (suffix-matched at resolution)
        self._by_parts = {}
        self._sync_summaries = None
        self._param_taints = {}
        for m in modules:
            self.add_module(m)

    @staticmethod
    def _node_key(rec: "FunctionRecord"):
        """Stable identity for a function node — id() does not survive
        pickling (the --index-cache round trip), source coordinates do."""
        return (rec.entry.path, rec.node.lineno, rec.node.col_offset,
                getattr(rec.node, "name", "<lambda>"))

    # ------------------------------------------------------------ building
    def add_module(self, analysis: ModuleAnalysis) -> ModuleEntry:
        path = os.path.normpath(analysis.path)
        old = self.modules.get(path)
        if old is not None:
            # re-adding a path (analyze_source against a long-lived
            # index) must EVICT the stale entry: a leftover twin makes
            # _lookup_module ambiguous (-> None) and silently kills
            # cross-file resolution, and memoized summaries/taints key
            # on source coordinates that may now mean different code
            bucket = self._by_parts.get(old.parts, [])
            if old in bucket:
                bucket.remove(old)
            if not bucket:
                self._by_parts.pop(old.parts, None)
            self._sync_summaries = None
            self._param_taints = {}
        entry = ModuleEntry(path, module_parts(path), analysis).collect()
        self.modules[path] = entry
        self._by_parts.setdefault(entry.parts, []).append(entry)
        analysis.program = self
        return entry

    def module_for(self, path: str):
        return self.modules.get(os.path.normpath(path))

    def _lookup_module(self, parts: tuple):
        """The unique indexed module whose dotted parts END with
        ``parts`` (import targets are canonical names; indexed keys may
        carry extra leading path segments)."""
        if not parts:
            return None
        exact = self._by_parts.get(parts)
        if exact and len(exact) == 1:
            return exact[0]
        candidates = [e for key, entries in self._by_parts.items()
                      for e in entries
                      if len(key) >= len(parts)
                      and key[-len(parts):] == parts]
        return candidates[0] if len(candidates) == 1 else None

    # --------------------------------------------------------- call graph
    def resolve_call(self, module: ModuleAnalysis, call: ast.Call):
        """Best-effort resolution of a call site to a FunctionRecord —
        local defs, ``self.method``, ``Class.method``, imported names and
        ``module.func`` across the indexed files.  None when dynamic."""
        entry = self.module_for(module.path)
        if entry is None:
            return None
        func = call.func
        if isinstance(func, ast.Name):
            local = module._resolve_name_to_def(func)
            if local is not None:
                return self._record_for(entry, local)
            imp = entry.imports.get(func.id)
            if imp is not None:
                return self._resolve_imported(imp)
            rec = entry.symbols.get(func.id)
            if rec is not None:
                return rec
            return None
        if isinstance(func, ast.Attribute):
            chain = qualifier_chain(func)
            if not chain:
                return None
            if chain == ["self"] or chain == ["cls"]:
                cls = self._enclosing_class(module, call)
                if cls is not None:
                    return entry.symbols.get(f"{cls.name}.{func.attr}")
                return None
            if len(chain) == 1 and chain[0] in entry.symbols \
                    and "." not in chain[0]:
                # ClassName.method in the same module
                rec = entry.symbols.get(f"{chain[0]}.{func.attr}")
                if rec is not None:
                    return rec
            # imported module alias: mod.func / pkg.sub.func
            imp = entry.imports.get(chain[0])
            if imp is None:
                return None
            base, sym = imp
            if sym is not None:
                # `from pkg import mod` then mod.func: the imported name
                # is itself a module
                base = base + (sym,)
            target = self._lookup_module(base + tuple(chain[1:]))
            if target is None and len(chain) > 1:
                target = self._lookup_module(base)
            if target is None:
                return None
            return target.symbols.get(func.attr)
        return None

    def _resolve_imported(self, imp):
        base, sym = imp
        if sym is None:
            return None
        target = self._lookup_module(base)
        if target is not None:
            return target.symbols.get(sym)
        return None

    def _record_for(self, entry: ModuleEntry, fn_node):
        for rec in entry.symbols.values():
            if rec.node is fn_node:
                return rec
        # nested def: not in the symbol table, record on the fly so
        # summaries still work for same-module nested helpers
        return FunctionRecord(fn_node, entry,
                              getattr(fn_node, "name", "<lambda>"))

    @staticmethod
    def _enclosing_class(module: ModuleAnalysis, node):
        cur = module.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = module.parents.get(cur)
        return None

    # ---------------------------------------------- per-parameter taint
    def param_taint(self, rec: FunctionRecord, param: str) -> frozenset:
        """Names in ``rec`` tainted by ``param`` alone (reaching-defs
        fixpoint via ModuleAnalysis._propagate)."""
        key = self._node_key(rec) + (param,)
        got = self._param_taints.get(key)
        if got is None:
            tainted = {param}
            rec.entry.analysis._propagate(rec.node, tainted)
            got = frozenset(tainted)
            self._param_taints[key] = got
        return got

    # ------------------------------------------------- TPS008 summaries
    def sync_summaries(self) -> dict:
        """node-id -> {param_name -> chain} where ``chain`` is a tuple of
        ``(qualname, path, line, description)`` hops ending at the host
        sync.  A parameter appears when a value derived from it reaches a
        host-syncing operation — directly, or through a resolvable call
        whose receiving parameter syncs (transitively, to a fixpoint)."""
        if self._sync_summaries is not None:
            return self._sync_summaries
        summaries: dict = {}
        records = []
        for entry in self.modules.values():
            seen = set()
            for rec in list(entry.symbols.values()):
                if rec.node in seen:
                    continue
                seen.add(rec.node)
                records.append(rec)
                direct = self._direct_syncs(rec)
                if direct:
                    summaries[self._node_key(rec)] = direct
        # propagate through the call graph to a fixpoint; first evidence
        # per parameter wins, so cycles terminate
        changed = True
        passes = 0
        while changed and passes <= len(records) + 1:
            changed = False
            passes += 1
            for rec in records:
                if self._propagate_calls(rec, summaries):
                    changed = True
        self._sync_summaries = summaries
        return summaries

    def summary_for(self, rec: FunctionRecord) -> dict:
        return self.sync_summaries().get(self._node_key(rec), {})

    def _direct_syncs(self, rec: FunctionRecord) -> dict:
        module = rec.entry.analysis
        out: dict = {}
        a = rec.node.args
        params = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        statics = module._static_argnames(rec.node)
        for param in params:
            if param in statics or param in out:
                continue
            taint = self.param_taint(rec, param)
            for node in module.iter_own_nodes(rec.node):
                if not isinstance(node, ast.Call):
                    continue
                desc = self._sync_desc(module, node, taint)
                if desc is not None:
                    out[param] = ((rec.qualname, rec.path, node.lineno,
                                   f"{desc} of a value derived from "
                                   f"parameter `{param}`"),)
                    break
        return out

    @staticmethod
    def _sync_desc(module: ModuleAnalysis, call: ast.Call, taint):
        func = call.func
        if (isinstance(func, ast.Name) and func.id in SYNC_SCALAR_CASTS
                and call.args
                and module.expr_tainted(call.args[0], taint)):
            return f"`{func.id}()`"
        if (isinstance(func, ast.Attribute) and func.attr in SYNC_METHODS
                and module.expr_tainted(func.value, taint)):
            return f"`.{func.attr}()`"
        if (module.info.is_numpy_attr(func)
                and any(module.expr_tainted(arg, taint)
                        for arg in call.args)):
            return f"`{ast.unparse(func)}()`"
        if (terminal_name(func) in SYNC_JAX_CALLS
                and isinstance(func, ast.Attribute)
                and (chain := qualifier_chain(func))
                and chain[0] in module.info.jax_aliases
                and any(module.expr_tainted(arg, taint)
                        for arg in call.args)):
            return f"`{ast.unparse(func)}()`"
        return None

    def _propagate_calls(self, rec: FunctionRecord, summaries) -> bool:
        """Lift callee summaries into ``rec``: a parameter of ``rec``
        whose taint flows into a syncing parameter of a resolvable callee
        syncs too, with the chain extended by one hop."""
        module = rec.entry.analysis
        mine = summaries.setdefault(self._node_key(rec), {})
        a = rec.node.args
        params = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        statics = module._static_argnames(rec.node)
        changed = False
        for node in module.iter_own_nodes(rec.node):
            if not isinstance(node, ast.Call):
                continue
            callee = self.resolve_call(module, node)
            if callee is None or callee.node is rec.node:
                continue
            callee_sum = summaries.get(self._node_key(callee))
            if not callee_sum:
                continue
            for arg_expr, callee_param in iter_argument_map(node, callee):
                if callee_param not in callee_sum:
                    continue
                for param in params:
                    if param in mine or param in statics:
                        continue
                    if module.expr_tainted(arg_expr,
                                           self.param_taint(rec, param)):
                        mine[param] = ((rec.qualname, rec.path,
                                        node.lineno,
                                        f"calls `{callee.qualname}()`"),
                                       ) + callee_sum[callee_param]
                        changed = True
        return changed

    # ------------------------------------------------ reaching defs/uses
    def resolve_local_value(self, module: ModuleAnalysis, name: ast.Name):
        """The defining expression of ``name`` by linear reaching-defs:
        the LAST assignment to the name above the use, in the enclosing
        function's own statements or the module body.  None when the
        name is rebound ambiguously or never assigned."""
        scope = module.parents.get(name)
        while scope is not None and not isinstance(
                scope, FUNCTION_NODES + (ast.Module,)):
            scope = module.parents.get(scope)
        if scope is None:
            return None
        best = None
        nodes = (module.iter_own_nodes(scope)
                 if isinstance(scope, FUNCTION_NODES)
                 else ast.walk(scope))
        for node in nodes:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            if node.lineno >= name.lineno:
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Name) and t.id == name.id:
                    if best is None or node.lineno > best.lineno:
                        best = node
        if best is None and isinstance(scope, FUNCTION_NODES):
            # fall back to a module-level constant
            mod_scope = module.tree
            for node in mod_scope.body:
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name) and t.id == name.id:
                        best = node
        return best.value if best is not None else None


def iter_argument_map(call: ast.Call, callee: FunctionRecord):
    """Yield ``(arg_expr, callee_param_name)`` pairs for a call site.
    Starred positionals make the mapping unreliable — positional pairing
    stops at the first ``*args``; keywords always map by name."""
    pos = 0
    for arg in call.args:
        if isinstance(arg, ast.Starred):
            break
        param = callee.positional_param(pos)
        if param is not None:
            yield arg, param
        pos += 1
    for kw in call.keywords:
        if kw.arg is None:
            continue
        param = callee.keyword_param(kw.arg)
        if param is not None:
            yield kw.value, param


def build_program_index(analyses) -> ProgramIndex:
    """Phase-1 entry point: index every parsed module."""
    return ProgramIndex(list(analyses))
