"""TPS012 — fault-point registry check.

Every ``faults.check("...")`` / ``faults.triggered("...")`` call site must
name a point registered in ``resilience/faults.FAULT_POINTS``: a typo'd
point name parses, runs, and simply NEVER FIRES — the injected-fault test
that was supposed to exercise a recovery path silently exercises nothing
(the fault-injection analog of TPS007's options-flag registry check).
The reverse direction — every registered point has at least one
call site — is a repo-level property and is enforced by the meta-test
``tests/test_tpslint.py::test_fault_registry_coverage`` built on this
module's :func:`fault_point_sites` helper.

The registry is read from ``resilience/faults.py`` by PARSING its AST (the
``FAULT_POINTS`` dict literal's string keys) — tpslint stays stdlib-only
and never imports framework packages (the package ``__init__`` pulls in
jax).  Dynamic point arguments (``faults.check(point)``) are not
checkable and stay silent.
"""

from __future__ import annotations

import ast
import functools
from pathlib import Path

from ..context import terminal_name
from .base import Rule, register

#: attribute names that count as fault-point hooks on a faults module
#: (apply_silent_fault is resilience/abft.py's trace-time applicator for
#: the silent kinds — its point argument names FAULT_POINTS entries too;
#: mesh_fault is the persistent-device-loss hook at the solve-program
#: boundary, point-name first, device ids second; delay_seconds is the
#: timing hook — 'comm.delay' latency injection, point-name first)
_HOOKS = ("check", "triggered", "apply_silent_fault", "mesh_fault",
          "delay_seconds")
#: module aliases the repo binds resilience.faults / resilience.abft to
_MODULE_NAMES = ("faults", "_faults", "abft", "_abft")

_FAULTS_REL = Path("mpi_petsc4py_example_tpu") / "resilience" / "faults.py"


@functools.lru_cache(maxsize=1)
def registered_fault_points() -> frozenset:
    """String keys of ``resilience/faults.FAULT_POINTS``, parsed from the
    module's AST.  Empty when the file (or the dict) cannot be found —
    the rule then has nothing to check against and stays silent (the
    coverage meta-test fails loudly on an empty registry instead)."""
    path = Path(__file__).resolve().parents[3] / _FAULTS_REL
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return frozenset()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "FAULT_POINTS" not in targets:
            continue
        if isinstance(node.value, ast.Dict):
            return frozenset(
                key.value for key in node.value.keys
                if isinstance(key, ast.Constant) and isinstance(key.value,
                                                                str))
    return frozenset()


def fault_point_sites(tree):
    """Yield ``(point_or_None, call_node)`` for every fault-point hook
    call in ``tree`` — ``point`` is the literal string argument, or None
    when the argument is dynamic (not statically checkable)."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOOKS):
            continue
        if terminal_name(node.func.value) not in _MODULE_NAMES:
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield arg.value, node
        else:
            yield None, node


@register
class FaultRegistryRule(Rule):
    id = "TPS012"
    name = "fault-point-registry"
    description = ("faults.check()/faults.triggered() call sites must name "
                   "a point registered in resilience/faults.FAULT_POINTS — "
                   "a typo'd point silently never fires")

    def check(self, module):
        known = registered_fault_points()
        if not known:
            return
        for point, node in fault_point_sites(module.tree):
            if point is not None and point not in known:
                yield self.finding(
                    node,
                    f"fault point {point!r} is not registered in "
                    "resilience/faults.FAULT_POINTS — the hook will never "
                    f"fire (known: {', '.join(sorted(known))}); register "
                    "the point or fix the name")
