"""TPS010 — grid-spec object coverage (ROADMAP, deferred from the
initial rule set; landed with the program-index dataflow work).

TPS006 checks ``grid=``/``BlockSpec`` literals AT the ``pallas_call``
site.  Real kernels (and the matrix-free user kernels ROADMAP item 4
will bring in) bundle their geometry into ``pl.GridSpec`` /
``pltpu.PrefetchScalarGridSpec`` objects constructed away from the call
and threaded through locals and kwargs — invisible to a call-site-only
check, and a rank mismatch still surfaces only as an opaque Mosaic
lowering error.

Checks, using the program index's reaching-defs to look through local
names (``spec = pl.BlockSpec(...)`` then ``in_specs=[spec]``, or a
module-level ``GRID = (4, 4)`` constant threaded into ``grid=``):

* **index_map arity** — a ``BlockSpec`` index_map inside a
  ``GridSpec`` must take one index per grid dimension; inside a
  ``PrefetchScalarGridSpec`` it takes ``num_scalar_prefetch``
  *additional* leading scalar-ref arguments (the TPU scalar-prefetch
  calling convention — see the Pallas grid documentation);
* **block rank** — a tuple-literal index_map body must return one block
  coordinate per ``block_shape`` dimension;
* **conflicting geometry** — ``pallas_call(..., grid_spec=..., grid=...)``
  (or ``in_specs=``/``out_specs=`` alongside ``grid_spec=``): the bundle
  already carries grid and specs; passing both silently ignores one set
  or raises far from the mistake.
"""

from __future__ import annotations

import ast

from ..context import terminal_name
from .base import Rule, register

GRID_SPEC_NAMES = {"GridSpec", "PrefetchScalarGridSpec"}


def _grid_rank(node):
    if isinstance(node, (ast.Tuple, ast.List)):
        return len(node.elts)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return 1
    return None


def _int_const(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


@register
class GridSpecRule(Rule):
    id = "TPS010"
    name = "grid-spec-coverage"
    description = ("pl.GridSpec/PrefetchScalarGridSpec objects constructed "
                   "away from the pallas_call site: index_map arity/rank "
                   "vs grid (+num_scalar_prefetch) mismatches, and "
                   "pallas_call given both grid_spec= and grid=/in_specs=")

    def check(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            if name in GRID_SPEC_NAMES:
                yield from self._check_spec(module, node,
                                            prefetch=(name ==
                                                      "PrefetchScalarGridSpec"))
            elif name == "pallas_call":
                yield from self._check_call_site(node)

    # ---------------------------------------------------- construction
    def _check_spec(self, module, call: ast.Call, prefetch: bool):
        grid = None
        nsp = 0
        for kw in call.keywords:
            if kw.arg == "grid":
                grid = _grid_rank(self._resolve(module, kw.value))
            elif kw.arg == "num_scalar_prefetch" and prefetch:
                nsp = _int_const(self._resolve(module, kw.value)) or 0
        for spec in self._blockspecs(module, call):
            block_shape = spec.args[0] if spec.args else None
            index_map = spec.args[1] if len(spec.args) > 1 else None
            for kw in spec.keywords:
                if kw.arg == "index_map":
                    index_map = kw.value
                elif kw.arg == "block_shape":
                    block_shape = kw.value
            block_shape = self._resolve(module, block_shape)
            if not isinstance(index_map, ast.Lambda):
                continue
            arity = len(index_map.args.args)
            want = None if grid is None else grid + nsp
            if want is not None and arity != want:
                extra = (f" + {nsp} scalar-prefetch ref(s)" if nsp else "")
                yield self.finding(
                    index_map,
                    f"BlockSpec index_map takes {arity} argument(s) but "
                    f"this {'PrefetchScalarGridSpec' if prefetch else 'GridSpec'} "
                    f"declares a rank-{grid} grid{extra} — index_map "
                    f"arity must be {want}")
            if (isinstance(block_shape, (ast.Tuple, ast.List))
                    and isinstance(index_map.body, ast.Tuple)
                    and len(index_map.body.elts) != len(block_shape.elts)):
                yield self.finding(
                    index_map,
                    f"BlockSpec index_map returns "
                    f"{len(index_map.body.elts)} block coordinates for a "
                    f"rank-{len(block_shape.elts)} block_shape — ranks "
                    "must match")

    def _blockspecs(self, module, call: ast.Call):
        """BlockSpec constructions inside in_specs/out_specs — literal
        or threaded through a local/module name (reaching-defs)."""
        for kw in call.keywords:
            if kw.arg not in ("in_specs", "out_specs"):
                continue
            for node in ast.walk(kw.value):
                resolved = self._resolve(module, node)
                if (isinstance(resolved, ast.Call)
                        and terminal_name(resolved.func) == "BlockSpec"):
                    yield resolved

    def _resolve(self, module, node):
        """Look through a Name to its defining expression via the
        program index's linear reaching-defs."""
        if isinstance(node, ast.Name) and module.program is not None:
            defined = module.program.resolve_local_value(module, node)
            if defined is not None:
                return defined
        return node

    # ------------------------------------------------------- call site
    def _check_call_site(self, call: ast.Call):
        kwargs = {kw.arg for kw in call.keywords if kw.arg}
        if "grid_spec" not in kwargs:
            return
        clash = sorted(kwargs & {"grid", "in_specs", "out_specs"})
        if clash:
            yield self.finding(
                call,
                f"pallas_call given both grid_spec= and "
                f"{'/'.join(clash)}= — the grid-spec bundle already "
                "carries the grid and block specs; passing both silently "
                "ignores one set or fails far from the mistake")
