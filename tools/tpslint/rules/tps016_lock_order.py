"""TPS016 — lock-order and dispatcher-thread shared-state discipline.

The serving tier is the one place the repo runs real threads: the
server's dispatcher loop (serving/server.py), the fleet router's
migration path (serving/fleet.py), and whatever the elastic-mesh
helpers grow next.  Two invariants keep it deadlock- and race-free, and
both are stated only in comments today (fleet.py: "Order: _move_lock
before _lock, never the reverse"):

* **Lock order** — when two of a class's locks nest, they must nest in
  ONE direction everywhere.  The rule collects the class's lock
  attributes (``self.x = threading.Lock()/RLock()/Condition()``), reads
  every syntactic ``with self.x:`` nesting (including the item order of
  ``with self.a, self.b:``), lets the FIRST nesting seen in source
  order establish the partial order, and flags any later acquisition
  that contradicts it — the classic ABBA deadlock shape.
* **Thread shared state** — a method a ``threading.Thread(target=
  self._loop)`` runs concurrently with the public API.  A field the
  class elsewhere touches under one of its locks is evidently
  lock-protected; a bare ``self.field = ...`` write to it inside the
  thread body is a race (the dispatcher publishing state the submit
  path reads under the condition variable).

Both checks are lexical and per-class: nesting through a method call
(``with self._session_lock: self._dispatch(...)`` where the callee
takes ``self._cv``) is invisible, as is a lock passed between objects —
conservative by design, like TPS008's dynamic-callee silence.  Error
tier: a finding is either a deadlock waiting for the right interleaving
or a torn read.
"""

from __future__ import annotations

import ast

from ..context import FUNCTION_NODES, terminal_name
from .base import Rule, register

#: constructors whose product participates in ``with`` lock discipline
_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"})


def _self_attr(node) -> str | None:
    """``X`` for an ``self.X`` expression, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _assign_target_attr(target) -> str | None:
    """The ``self.X`` base of an assignment target, unwrapping
    subscripts (``self._stats["expired"] += 1`` writes ``_stats``)."""
    while isinstance(target, ast.Subscript):
        target = target.value
    return _self_attr(target)


def _lock_attrs(cls: ast.ClassDef) -> set:
    """Attributes assigned from a threading lock constructor anywhere in
    the class body (canonically ``__init__``)."""
    out = set()
    for n in ast.walk(cls):
        if not (isinstance(n, ast.Assign) and isinstance(n.value, ast.Call)):
            continue
        if terminal_name(n.value.func) in _LOCK_CTORS:
            for t in n.targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.add(attr)
    return out


def _thread_targets(cls: ast.ClassDef) -> set:
    """Method names passed as ``threading.Thread(target=self.X)`` — the
    class's concurrent entry points."""
    out = set()
    for n in ast.walk(cls):
        if not (isinstance(n, ast.Call)
                and terminal_name(n.func) == "Thread"):
            continue
        for kw in n.keywords:
            if kw.arg == "target":
                attr = _self_attr(kw.value)
                if attr is not None:
                    out.add(attr)
    return out


def _class_methods(cls: ast.ClassDef):
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield stmt


def _walk_withs(func, locks):
    """Yield ``(held_tuple, lock_name, item_node)`` for every lock
    acquisition in ``func``, with the stack of locks already held at
    that point — syntactic nesting plus same-``with`` item order."""

    def visit(node, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FUNCTION_NODES + (ast.ClassDef,)):
                continue                       # separate execution context
            if isinstance(child, (ast.With, ast.AsyncWith)):
                inner = list(held)
                for item in child.items:
                    name = _self_attr(item.context_expr)
                    if name is not None and name in locks:
                        yield tuple(inner), name, item.context_expr
                        inner.append(name)
                yield from visit(child, inner)
            else:
                yield from visit(child, held)

    yield from visit(func, [])


def _locked_accesses(cls: ast.ClassDef, locks) -> set:
    """Every ``self.X`` attribute touched inside a ``with self.<lock>:``
    block anywhere in the class — the evidently lock-protected fields."""
    protected = set()
    for func in _class_methods(cls):
        for node in ast.walk(func):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(_self_attr(i.context_expr) in locks
                       for i in node.items):
                continue
            for sub in ast.walk(node):
                attr = _self_attr(sub)
                if attr is not None and attr not in locks:
                    protected.add(attr)
    return protected


def _unlocked_writes(func, locks):
    """``(attr, node)`` for every ``self.X`` write in ``func`` made with
    NO class lock held (lexically)."""

    def visit(node, depth):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FUNCTION_NODES + (ast.ClassDef,)):
                continue
            d = depth
            if isinstance(child, (ast.With, ast.AsyncWith)):
                if any(_self_attr(i.context_expr) in locks
                       for i in child.items):
                    d = depth + 1
            elif depth == 0:
                targets = []
                if isinstance(child, ast.Assign):
                    targets = child.targets
                elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
                    targets = [child.target]
                for t in targets:
                    attr = _assign_target_attr(t)
                    if attr is not None:
                        yield attr, child
            yield from visit(child, d)

    yield from visit(func, 0)


@register
class LockOrderRule(Rule):
    id = "TPS016"
    name = "lock-order"
    description = ("serving-tier thread discipline: every pair of a "
                   "class's locks must nest in one direction only, and "
                   "a Thread-target body must not write lock-protected "
                   "fields bare")
    severity = "error"

    def check(self, module):
        for cls in ast.walk(module.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(cls)

    # ------------------------------------------------------------ lock order
    def _check_class(self, cls):
        locks = _lock_attrs(cls)
        if not locks:
            return
        # established partial order: edge a -> b means "a held while
        # acquiring b"; first sighting (source order) wins, recorded
        # with its location so the inversion message can cite it
        order: dict = {}
        for func in _class_methods(cls):
            for held, name, node in _walk_withs(func, locks):
                for outer in held:
                    if outer == name:
                        continue              # RLock re-entry: not an edge
                    if self._reaches(order, name, outer):
                        first = order[(name, outer)] if (name, outer) \
                            in order else None
                        where = (f" (order established at line "
                                 f"{first.lineno})") if first is not None \
                            else " (by a chain of earlier nestings)"
                        yield self.finding(
                            node,
                            f"lock-order inversion in {cls.name}: "
                            f"self.{name} acquired while holding "
                            f"self.{outer}, but the established order "
                            f"is self.{name} before "
                            f"self.{outer}{where} — an ABBA deadlock "
                            f"under the right interleaving")
                    else:
                        order.setdefault((outer, name), node)
        yield from self._check_thread_writes(cls, locks)

    @staticmethod
    def _reaches(order, src, dst) -> bool:
        """Is there a path src -> ... -> dst in the established order?"""
        seen, stack = set(), [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(b for (a, b) in order if a == cur)
        return False

    # --------------------------------------------------- thread shared state
    def _check_thread_writes(self, cls, locks):
        bodies = _thread_targets(cls)
        if not bodies:
            return
        protected = _locked_accesses(cls, locks)
        for func in _class_methods(cls):
            if func.name not in bodies:
                continue
            for attr, node in _unlocked_writes(func, locks):
                if attr in protected and attr not in locks:
                    yield self.finding(
                        node,
                        f"thread-body write without a lock: "
                        f"{cls.name}.{func.name} runs on its own "
                        f"thread and assigns self.{attr} bare, but "
                        f"self.{attr} is accessed under a lock "
                        f"elsewhere in the class — take the lock "
                        f"around the write")
