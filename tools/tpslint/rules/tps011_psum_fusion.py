"""TPS011 — adjacent-``lax.psum`` fusion advisory (warn tier).

Every ``lax.psum`` is a cross-device reduction barrier; two independent
psums over the same axis in adjacent statements cost two collective round
trips where ONE stacked reduction (``lax.psum(jnp.stack([a, b]), axis)``
— the krylov.py single-psum idiom, SURVEY.md §3.5) costs one.  The lint
analog of the round-6 fused-reduction kernel discipline.

Advisory only (``severity = "warn"``): a separate psum is sometimes the
clearer code and the latency can be negligible off the hot path — the CI
``--warn-budget`` keeps the *count* from growing silently without
blocking existing, considered call sites.

The check is deliberately conservative about dependence: when the second
psum's operand mentions any name the first psum's statement assigns, the
reductions are sequentially dependent and cannot fuse — no finding.
"""

from __future__ import annotations

import ast

from ..context import FUNCTION_NODES, terminal_name
from .base import Rule, register

_PSUM_NAMES = {"psum", "pmax", "pmin", "pmean"}


def _psum_calls(stmt: ast.stmt):
    """(call, axis_repr) for every reduction-collective call in ``stmt``,
    not descending into nested function definitions (their bodies are
    separate traced scopes)."""
    out = []
    if isinstance(stmt, FUNCTION_NODES + (ast.ClassDef,)):
        return out      # a def/class STATEMENT executes no reductions
    stack = [stmt]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FUNCTION_NODES):
                continue
            stack.append(child)
        if not isinstance(node, ast.Call):
            continue
        name = terminal_name(node.func)
        if name not in _PSUM_NAMES:
            continue
        axis = node.args[1] if len(node.args) > 1 else None
        for kw in node.keywords:
            if kw.arg == "axis_name":
                axis = kw.value
        if axis is not None:
            out.append((node, name, ast.unparse(axis)))
    return out


def _assigned_names(stmt: ast.stmt):
    names = set()
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                             ast.NamedExpr)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
    return names


def _used_names(expr: ast.expr):
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _nested(c1: ast.Call, c2: ast.Call) -> bool:
    """One reduction sits inside the other's argument tree."""
    return any(n is c2 for n in ast.walk(c1)) or \
        any(n is c1 for n in ast.walk(c2))


@register
class PsumFusionRule(Rule):
    id = "TPS011"
    name = "adjacent-psum-fusion"
    description = ("independent lax.psum/pmax/pmin calls on the same axis "
                   "in adjacent statements could fuse into one stacked "
                   "reduction (advisory — warn tier)")
    severity = "warn"

    def check(self, module):
        flagged = set()      # call-node ids: one advisory per call site
        for body in self._statement_lists(module.tree):
            prev = None      # (stmt_index, stmt, calls)
            for i, stmt in enumerate(body):
                calls = _psum_calls(stmt)
                if not calls:
                    continue
                # several independent psums INSIDE one statement — nested
                # calls (`psum(x / psum(y, ax), ax)`: the normalization
                # idiom) are sequentially dependent, never fusible
                for (c1, n1, ax1), (c2, n2, ax2) in zip(calls, calls[1:]):
                    if (ax1 == ax2 and id(c2) not in flagged
                            and not _nested(c1, c2)):
                        flagged.add(id(c2))
                        yield self._advise(c2, n1, n2, ax2)
                if (prev is not None and i - prev[0] == 1
                        and self._independent(prev[1], stmt, calls)):
                    for c2, n2, ax2 in calls:
                        match = [n1 for _, n1, ax1 in prev[2]
                                 if ax1 == ax2]
                        if match and id(c2) not in flagged:
                            flagged.add(id(c2))
                            yield self._advise(c2, match[0], n2, ax2)
                            break
                prev = (i, stmt, calls)

    @staticmethod
    def _statement_lists(tree):
        for node in ast.walk(tree):
            for fieldname in ("body", "orelse", "finalbody"):
                body = getattr(node, fieldname, None)
                if isinstance(body, list) and body:
                    yield body

    @staticmethod
    def _independent(stmt_a, stmt_b, calls_b) -> bool:
        """The later psums don't consume names the earlier statement
        binds — a data dependence makes the pair unfusible."""
        assigned = _assigned_names(stmt_a)
        if not assigned:
            return True
        for call, _, _ in calls_b:
            if call.args and _used_names(call.args[0]) & assigned:
                return False
        return True

    def _advise(self, node, name1, name2, axis_repr):
        return self.finding(
            node,
            f"adjacent `{name1}`/`{name2}` on axis {axis_repr} — "
            "independent reductions can stack into ONE collective "
            "(`lax.psum(jnp.stack([...]), axis)`, the krylov.py "
            "single-psum idiom): each extra psum is a device-sync round "
            "trip in the hot loop")
