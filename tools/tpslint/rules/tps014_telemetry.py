"""TPS014 — telemetry-coverage check.

Two registries back the observability layer, and both are enforced here
(the TPS007/TPS012 pattern applied to telemetry):

1. **Name registry** — every ``span("...")`` / ``start_span("...")`` /
   ``registry.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")``
   call site must name an entry of ``telemetry/names.NAMES``: a typo'd
   span or metric name otherwise records into a parallel universe — the
   dashboards and traces built on the registered name silently show
   nothing. (The runtime ALSO validates, but only on the paths a test
   happens to execute; the lint covers every site statically.)

2. **Flight fault coverage** — ``telemetry/names.FLIGHT_FAULT_POINTS``
   must cover every key of ``resilience/faults.FAULT_POINTS``: a fault
   point with no flight-recorder event site means a fired fault of that
   kind leaves no post-mortem trace. Checked when linting
   ``telemetry/names.py`` itself (both sides parsed from their ASTs —
   tpslint stays stdlib-only).

The reverse directions — every registered name has at least one call
site, and every FLIGHT_FAULT_POINTS entry is a real fault point — are
repo-level properties enforced by the meta-tests in
``tests/test_tpslint.py`` built on this module's helpers.

Dynamic name arguments (``span(name)``) are not statically checkable
and stay silent, like TPS007/TPS012.
"""

from __future__ import annotations

import ast
import functools
from pathlib import Path

from ..context import terminal_name
from .base import Rule, register
from .tps012_fault_registry import registered_fault_points

#: call shapes that take a telemetry NAME as their first argument
_SPAN_HOOKS = ("span", "start_span")
_METRIC_HOOKS = ("counter", "gauge", "histogram")
#: receivers the repo binds the span API / metrics registry to
_SPAN_RECEIVERS = ("telemetry", "_telemetry", "spans", "_spans")
_METRIC_RECEIVERS = ("registry", "_registry", "_REG", "metrics",
                     "_metrics")

_NAMES_REL = Path("mpi_petsc4py_example_tpu") / "telemetry" / "names.py"


@functools.lru_cache(maxsize=1)
def _names_module_tree():
    path = Path(__file__).resolve().parents[3] / _NAMES_REL
    try:
        return ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None


def _assigned(tree, target: str):
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == target
                        for t in node.targets)):
            return node.value
    return None


@functools.lru_cache(maxsize=1)
def registered_telemetry_names() -> frozenset:
    """String keys of ``telemetry/names.NAMES``, parsed from the module
    AST. Empty when unreadable — the rule then stays silent and the
    coverage meta-test fails loudly instead."""
    tree = _names_module_tree()
    if tree is None:
        return frozenset()
    value = _assigned(tree, "NAMES")
    if isinstance(value, ast.Dict):
        return frozenset(k.value for k in value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str))
    return frozenset()


@functools.lru_cache(maxsize=1)
def flight_fault_points() -> frozenset:
    """``telemetry/names.FLIGHT_FAULT_POINTS``, parsed from the AST."""
    tree = _names_module_tree()
    if tree is None:
        return frozenset()
    value = _assigned(tree, "FLIGHT_FAULT_POINTS")
    if isinstance(value, (ast.Tuple, ast.List)):
        return frozenset(e.value for e in value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str))
    return frozenset()


def telemetry_name_sites(tree):
    """Yield ``(name_or_None, call_node)`` for every span/metric call
    site in ``tree`` — ``None`` when the name argument is dynamic."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        func = node.func
        hook = terminal_name(func)
        if hook in _SPAN_HOOKS:
            # module-qualified only (_telemetry.span / telemetry.span):
            # a bare function that happens to be called span() is
            # somebody else's API
            if not (isinstance(func, ast.Attribute)
                    and terminal_name(func.value) in _SPAN_RECEIVERS):
                continue
        elif hook in _METRIC_HOOKS:
            if not (isinstance(func, ast.Attribute)
                    and terminal_name(func.value) in _METRIC_RECEIVERS):
                continue
        else:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield arg.value, node
        else:
            yield None, node


@register
class TelemetryCoverageRule(Rule):
    id = "TPS014"
    name = "telemetry-coverage"
    description = ("span()/registry.counter()/gauge()/histogram() call "
                   "sites must name an entry of telemetry/names.NAMES "
                   "(a typo'd name records into a parallel universe), "
                   "and FLIGHT_FAULT_POINTS must cover every "
                   "resilience/faults.FAULT_POINTS key")

    def check(self, module):
        known = registered_telemetry_names()
        if not known:
            return
        for name, node in telemetry_name_sites(module.tree):
            if name is not None and name not in known:
                yield self.finding(
                    node,
                    f"telemetry name {name!r} is not registered in "
                    "telemetry/names.NAMES — the span/metric would "
                    "record under an unregistered name; register it or "
                    "fix the spelling")
        # flight coverage: checked once, on the names module itself
        if str(module.path).replace("\\", "/").endswith(
                "telemetry/names.py"):
            missing = registered_fault_points() - flight_fault_points()
            if missing:
                yield self.finding(
                    module.tree,
                    "FLIGHT_FAULT_POINTS is missing fault point(s) "
                    f"{sorted(missing)} registered in resilience/faults."
                    "FAULT_POINTS — every fault point must have a "
                    "flight-recorder event site (telemetry.flight."
                    "record_fault covers the listed points)")
