"""TPS013 — donation safety: use-after-donation of solve buffers.

The solve programs DONATE their initial-iterate argument
(``build_ksp_program(..., donate=True)`` -> ``jax.jit(...,
donate_argnums=...)``): after dispatch the donated buffer is deleted —
its storage belongs to the program's output.  A stale reference reads a
deleted array and fails (or worse, on some runtimes, reads garbage)
far from the donation site.  PR 6's ``resilience/fallback.py`` bug was
exactly this: the pristine-guess snapshot ``x0 = x.data`` captured a
BARE reference; after the first donated stage consumed the buffer,
every later escalation re-seeded from a deleted array.  Found by hand
then; this rule finds it structurally.

Tracked provenance (the program index's intraprocedural lattice):

* ``prog = build_ksp_program(..., donate=True)`` (or ``_many``) makes
  ``prog`` a *donate-armed program*.  Calling it consumes its donated
  operand — the ``x0=``/``X0=`` keyword, or the LAST bare-name
  positional argument (the repo's calling convention:
  ``prog(mat_arrays, pc_arrays, b.data, x0d, rtol, ...)`` — trailing
  scalars are never bare names).  Any later read of that name is an
  error until it is rebound.
* ``ksp.solve(b, x)`` / ``ksp.solve_many(B, X)`` donate ``x.data``
  internally and rebind it to the program output — ``x`` itself stays
  valid, but any name previously bound to BARE ``x.data`` (not wrapped
  in ``jnp.copy``/``jnp.array``) is a deleted array afterwards: reading
  it is an error.
* ``SolveServer`` dispatch (``srv.submit(...)``/``srv.solve(...)`` on a
  name constructed via ``SolveServer(...)``) likewise invalidates bare
  ``.data`` aliases of its vector arguments — served sessions run the
  donated paths.

The walk is branch-aware (an ``if`` arm that ``raise``s contributes no
state downstream — the ``ksp.py`` idiom of dispatching a fault branch
and raising is clean) and runs loop bodies twice, so a snapshot taken
before a loop and re-read after the first donated solve inside it — the
PR-6 shape — is caught.  Traced contexts are skipped: donation is a
host-boundary concern, and inside the program the operand is live.
"""

from __future__ import annotations

import ast

from ..context import FUNCTION_NODES, terminal_name
from .base import Rule, register

#: builders whose donate=True literal arms the returned program
_BUILDERS = {"build_ksp_program", "build_ksp_program_many"}
#: method names that dispatch a donated solve on any receiver
_SOLVE_METHODS = {"solve", "solve_many"}
#: copy wrappers that break the alias (a copied snapshot is safe)
_COPY_CALLS = {"copy", "array", "asarray"}


class _Env:
    """Provenance state at one program point."""

    __slots__ = ("progs", "servers", "aliases", "consumed")

    def __init__(self):
        self.progs = {}       # name -> builder line
        self.servers = set()  # names holding a SolveServer
        self.aliases = {}     # name -> owner expr string ("x" for x.data)
        self.consumed = {}    # name -> reason string

    def copy(self):
        env = _Env()
        env.progs = dict(self.progs)
        env.servers = set(self.servers)
        env.aliases = dict(self.aliases)
        env.consumed = dict(self.consumed)
        return env

    def absorb(self, other):
        self.progs.update(other.progs)
        self.servers |= other.servers
        self.aliases.update(other.aliases)
        self.consumed.update(other.consumed)

    def kill(self, name: str):
        self.progs.pop(name, None)
        self.servers.discard(name)
        self.aliases.pop(name, None)
        self.consumed.pop(name, None)


_TERMINATORS = (ast.Raise, ast.Return, ast.Break, ast.Continue)


@register
class DonationSafetyRule(Rule):
    id = "TPS013"
    name = "use-after-donation"
    description = ("reading a binding after it was donated into a "
                   "donate=-armed solve program (build_ksp_program(..., "
                   "donate=True) calls, KSP.solve/solve_many donated "
                   "paths, SolveServer dispatch) without an intervening "
                   "jnp.copy/rebind")

    def check(self, module):
        self._reported = set()
        self._found = []
        scopes = [module.tree]
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if module.context_for(node) is None:
                    scopes.append(node)
        for scope in scopes:
            body = scope.body if isinstance(scope.body, list) else []
            self._walk_block(module, body, _Env())
        yield from self._found

    # ------------------------------------------------------------ walker
    def _walk_block(self, module, stmts, env) -> bool:
        """Returns True when the block terminates (raise/return/...)."""
        for stmt in stmts:
            if isinstance(stmt, _TERMINATORS):
                for child in ast.iter_child_nodes(stmt):
                    self._visit_expr(module, child, env)
                return True
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue        # separate scope
            if isinstance(stmt, ast.If):
                self._visit_expr(module, stmt.test, env)
                e_body, e_else = env.copy(), env.copy()
                t_body = self._walk_block(module, stmt.body, e_body)
                t_else = self._walk_block(module, stmt.orelse, e_else)
                merged = _Env()
                if not t_body:
                    merged.absorb(e_body)
                if not t_else:
                    merged.absorb(e_else)
                if t_body and t_else:
                    return True
                env.progs, env.servers = merged.progs, merged.servers
                env.aliases, env.consumed = merged.aliases, merged.consumed
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._visit_expr(module, stmt.iter, env)
                self._bind_targets(stmt.target, env, None, module)
                pre = env.copy()
                # two passes: state flowing around the back edge (a
                # donation on iteration 1 poisons a read early in
                # iteration 2 — the PR-6 fallback.py shape)
                self._walk_block(module, stmt.body, env)
                self._walk_block(module, stmt.body, env)
                env.absorb(pre)
                self._walk_block(module, stmt.orelse, env)
                continue
            if isinstance(stmt, ast.While):
                self._visit_expr(module, stmt.test, env)
                pre = env.copy()
                self._walk_block(module, stmt.body, env)
                self._walk_block(module, stmt.body, env)
                env.absorb(pre)
                self._walk_block(module, stmt.orelse, env)
                continue
            if isinstance(stmt, ast.Try):
                self._walk_block(module, stmt.body, env)
                for handler in stmt.handlers:
                    e_h = env.copy()
                    self._walk_block(module, handler.body, e_h)
                    env.absorb(e_h)
                self._walk_block(module, stmt.orelse, env)
                self._walk_block(module, stmt.finalbody, env)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._visit_expr(module, item.context_expr, env)
                    if item.optional_vars is not None:
                        self._bind_targets(item.optional_vars, env, None,
                                           module)
                self._walk_block(module, stmt.body, env)
                continue
            if isinstance(stmt, ast.Assign):
                self._visit_expr(module, stmt.value, env)
                for t in stmt.targets:
                    self._bind_targets(t, env, stmt.value, module)
                continue
            if isinstance(stmt, ast.AnnAssign):
                if stmt.value is not None:
                    self._visit_expr(module, stmt.value, env)
                    self._bind_targets(stmt.target, env, stmt.value, module)
                continue
            if isinstance(stmt, ast.AugAssign):
                self._visit_expr(module, stmt.value, env)
                self._visit_expr(module, stmt.target, env)
                self._bind_targets(stmt.target, env, None, module)
                continue
            if isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        env.kill(t.id)
                continue
            for child in ast.iter_child_nodes(stmt):
                self._visit_expr(module, child, env)
        return False

    # -------------------------------------------------- expression visit
    def _visit_expr(self, module, expr, env):
        """Report reads of consumed names, then apply donation events of
        any calls inside ``expr`` (reads happen before the dispatch)."""
        if expr is None or isinstance(expr, ast.expr_context):
            return
        for node in self._walk_no_lambda(expr):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in env.consumed):
                self._report(node, env.consumed[node.id])
        for node in self._walk_no_lambda(expr):
            if isinstance(node, ast.Call):
                self._apply_call(module, node, env)

    @staticmethod
    def _walk_no_lambda(expr):
        stack = [expr]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Lambda):
                    continue        # deferred body
                stack.append(child)

    def _apply_call(self, module, call: ast.Call, env):
        func = call.func
        # --- a donate-armed program call consumes its donated operand
        if isinstance(func, ast.Name) and func.id in env.progs:
            donated = None
            for kw in call.keywords:
                if kw.arg in ("x0", "X0") and isinstance(kw.value, ast.Name):
                    donated = kw.value
            if donated is None:
                names = [a for a in call.args if isinstance(a, ast.Name)]
                if names:
                    donated = names[-1]
            if donated is not None:
                env.consumed[donated.id] = (
                    f"donated into `{func.id}(...)` (a donate=True "
                    f"program built at line {env.progs[func.id]}) at "
                    f"line {call.lineno}")
            return
        if not isinstance(func, ast.Attribute):
            return
        recv = terminal_name(func.value)
        # --- SolveServer dispatch: bare .data aliases of vector args die
        if recv in env.servers and func.attr in ("submit", "solve"):
            arg_names = {a.id for a in call.args
                         if isinstance(a, ast.Name)}
            self._stale_aliases(env, arg_names, call,
                                f"`{recv}.{func.attr}(...)` (SolveServer "
                                "dispatch runs the donated solve paths)")
            return
        # --- KSP.solve(b, x) / solve_many(B, X): x.data is donated and
        #     internally rebound; stale pre-call aliases of it die
        if func.attr in _SOLVE_METHODS and len(call.args) >= 2 \
                and isinstance(call.args[1], ast.Name):
            self._stale_aliases(env, {call.args[1].id}, call,
                                f"`{ast.unparse(func)}({ast.unparse(call.args[0])}, "
                                f"{call.args[1].id})` (the donated solve "
                                f"path consumes `{call.args[1].id}.data`)")

    def _stale_aliases(self, env, owner_names, call, what):
        for alias, owner in list(env.aliases.items()):
            if owner in owner_names:
                env.consumed[alias] = (
                    f"a bare alias of `{owner}.data`, which was donated "
                    f"by {what} at line {call.lineno}")
                del env.aliases[alias]

    # ----------------------------------------------------------- binding
    def _bind_targets(self, target, env, value, module):
        if isinstance(target, ast.Name):
            env.kill(target.id)
            state = self._provenance(value, env)
            if state is not None:
                kind, payload = state
                if kind == "prog":
                    env.progs[target.id] = payload
                elif kind == "server":
                    env.servers.add(target.id)
                elif kind == "alias":
                    env.aliases[target.id] = payload
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_targets(elt, env, None, module)
        elif isinstance(target, ast.Starred):
            self._bind_targets(target.value, env, None, module)
        # Attribute/Subscript targets bind no local name

    @staticmethod
    def _provenance(value, env):
        if value is None:
            return None
        if isinstance(value, ast.Call):
            name = terminal_name(value.func)
            if name in _BUILDERS:
                donate = next((kw.value for kw in value.keywords
                               if kw.arg == "donate"), None)
                if (isinstance(donate, ast.Constant)
                        and donate.value is True):
                    return ("prog", value.lineno)
            if name == "SolveServer":
                return ("server", None)
            return None
        if isinstance(value, ast.Name):
            if value.id in env.progs:
                return ("prog", env.progs[value.id])
            if value.id in env.servers:
                return ("server", None)
            return None
        if (isinstance(value, ast.Attribute) and value.attr == "data"
                and isinstance(value.ctx, ast.Load)):
            return ("alias", ast.unparse(value.value))
        return None

    # --------------------------------------------------------- reporting
    def _report(self, node, reason):
        if id(node) in self._reported:
            return
        self._reported.add(id(node))
        self._found.append(self.finding(
            node,
            f"read of `{node.id}` after donation — it is {reason}; the "
            "buffer is deleted once the donated program dispatches. "
            "Snapshot with `jnp.copy(...)` before the donating call, or "
            "rebind the name from the program's output"))
