"""TPS008 — interprocedural host-sync reachability (ROADMAP, deferred
from the initial rule set; landed with the program-index work it
needed).

TPS001 lints each traced function body locally: ``float(x)`` inside a
jitted def.  But the repo's real host syncs hide behind calls — a
module-level helper that does ``np.linalg.norm(v)`` is perfectly fine
on host paths and a trace-time concretization error (or a silent
per-iteration device->host sync) the moment a jitted/``shard_map``/
Pallas region calls it with a traced value.  Per-function AST visitors
structurally cannot see this; the program index's call graph can.

The check: for every call site inside a traced context, resolve the
callee through :class:`~tools.tpslint.program.ProgramIndex` (across
files), look up its *sync summary* — which of its parameters flow,
transitively through further calls, into a host-syncing operation
(``float()``/``.item()``/``.block_until_ready()``/``np.*``/
``jax.device_get``) — and flag the call when a TRACED argument lands on
a syncing parameter.  The finding message carries the full call chain
down to the syncing operation, so a three-hop sync reads as a path, not
a mystery.

Precision notes: summaries are per-parameter (a helper that syncs its
``rtol`` config scalar does not poison calls that pass it a traced
``x`` elsewhere), callees that are themselves traced contexts are
skipped (their bodies are TPS001's domain), and host-callback targets
(``io_callback`` et al.) are exempt — they run on host by design.
"""

from __future__ import annotations

import ast

from ..program import iter_argument_map
from .base import Rule, register


@register
class InterproceduralSyncRule(Rule):
    id = "TPS008"
    name = "interprocedural-host-sync"
    description = ("a host-syncing operation (float()/.item()/"
                   ".block_until_ready()/np.*/jax.device_get) in any "
                   "function transitively reachable from a jit/shard_map/"
                   "pallas_call region, reported with the full call chain")

    def check(self, module):
        index = module.program
        if index is None:
            return
        for ctx in module.contexts:
            for node in module.iter_own_nodes(ctx.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = index.resolve_call(module, node)
                if callee is None:
                    continue
                if callee.is_traced() or callee.is_host_target():
                    # traced callee bodies are TPS001's domain; host
                    # callback targets run on host by design
                    continue
                summary = index.summary_for(callee)
                if not summary:
                    continue
                for arg_expr, param in iter_argument_map(node, callee):
                    if param not in summary:
                        continue
                    if module.expr_tainted(arg_expr, ctx.tainted):
                        yield self.finding(
                            node,
                            self._message(ctx, node, callee, param,
                                          summary[param]))
                        break

    def _message(self, ctx, call, callee, param, chain):
        where = (f"a function nested in a traced context (`{ctx.name}`)"
                 if ctx.reason == "enclosing"
                 else f"a `{ctx.reason}` context (`{ctx.name}`)")
        hops = [f"`{ctx.name}` calls `{callee.qualname}()` "
                f"({ctx_path(ctx, call)})"]
        for qual, path, line, desc in chain:
            hops.append(f"`{qual}` ({path}:{line}) {desc}")
        return (f"call into `{callee.qualname}` from {where} passes a "
                f"traced value to parameter `{param}`, which reaches a "
                f"host sync — call chain: " + " -> ".join(hops) +
                "; hoist the sync out of the traced region, use the jnp "
                "equivalent, or pass a static value")


def ctx_path(ctx, call) -> str:
    return f"line {call.lineno}"
