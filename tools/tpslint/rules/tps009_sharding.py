"""TPS009 — shard_map sharding-spec consistency (ROADMAP, deferred from
the initial rule set; landed with the multi-chip weak-scaling work this
rule directly guards).

Two statically-checkable shard_map hazards:

* **in_specs arity vs wrapped signature** — ``shard_map(fn, in_specs,
  out_specs)`` zips ``in_specs`` against ``fn``'s positional parameters;
  a spec tuple that is longer or shorter than the signature fails only
  at trace time, on the first real mesh, with a pytree-mismatch error
  pointing nowhere near the call site. Checked whenever ``fn`` resolves
  to a def in an enclosing scope (``*args`` signatures and dynamic
  callables are skipped) and the specs are a tuple/list literal.

* **P(axis) axes must exist in the enclosing mesh** — a
  ``PartitionSpec`` naming an axis no ``Mesh`` in the module defines
  shards nothing (or aborts) at run time. Only LITERAL axis names are
  comparable statically, and only when the module constructs at least
  one ``Mesh`` with literal ``axis_names`` — the repo's production idiom
  (threading ``DeviceComm.axis``) is dynamic and stays out of scope
  (TPS003 separately flags literal axis names at collective sites).
"""

from __future__ import annotations

import ast

from ..context import terminal_name
from .base import Rule, register


def _mesh_axis_literals(tree) -> set:
    """Literal axis names of every Mesh(...) construction in the module:
    ``Mesh(devs, ("x", "y"))`` / ``Mesh(devs, axis_names=("x",))`` /
    ``Mesh(devs, "x")``."""
    axes = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and terminal_name(node.func) == "Mesh"):
            continue
        cand = None
        if len(node.args) >= 2:
            cand = node.args[1]
        for kw in node.keywords:
            if kw.arg == "axis_names":
                cand = kw.value
        if cand is None:
            continue
        for c in ast.walk(cand):
            if isinstance(c, ast.Constant) and isinstance(c.value, str):
                axes.add(c.value)
    return axes


def _spec_axis_literals(spec_node):
    """(axis_literal, P_call_node) pairs inside an in_specs/out_specs
    expression: string constants appearing as arguments of
    ``P(...)`` / ``PartitionSpec(...)`` calls."""
    for node in ast.walk(spec_node):
        if not (isinstance(node, ast.Call)
                and terminal_name(node.func) in ("P", "PartitionSpec")):
            continue
        for arg in node.args:
            for c in ast.walk(arg):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    yield c.value, node


def _positional_arity(fn_def):
    """(min, max) positional-argument count of a def, or None when it
    takes *args (arity unbounded — not checkable)."""
    a = fn_def.args
    if a.vararg is not None:
        return None
    pos = len(a.posonlyargs) + len(a.args)
    return (pos - len(a.defaults), pos)


@register
class ShardingSpecRule(Rule):
    id = "TPS009"
    name = "sharding-spec-consistency"
    description = ("shard_map in_specs arity must match the wrapped "
                   "function's signature, and literal P(axis) names must "
                   "be axes some enclosing Mesh defines")

    def check(self, module):
        mesh_axes = _mesh_axis_literals(module.tree)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "shard_map"
                    and node.args):
                continue
            # the repo spells both jax.shard_map(fn, mesh=..., in_specs=...)
            # and comm.shard_map(fn, in_specs, out_specs) — positional
            # index 1/2 covers the comm idiom, keywords the jax one
            in_specs = out_specs = None
            if len(node.args) >= 2:
                in_specs = node.args[1]
            if len(node.args) >= 3:
                out_specs = node.args[2]
            for kw in node.keywords:
                if kw.arg == "in_specs":
                    in_specs = kw.value
                elif kw.arg == "out_specs":
                    out_specs = kw.value

            # ---- arity: in_specs tuple literal vs resolvable def ----
            fn_def = None
            if isinstance(node.args[0], ast.Name):
                fn_def = module._resolve_name_to_def(node.args[0])
            elif isinstance(node.args[0], ast.Lambda):
                fn_def = node.args[0]
            if (fn_def is not None
                    and isinstance(in_specs, (ast.Tuple, ast.List))):
                arity = _positional_arity(fn_def)
                if arity is not None:
                    lo, hi = arity
                    n = len(in_specs.elts)
                    if not lo <= n <= hi:
                        want = (f"{hi}" if lo == hi else f"{lo}..{hi}")
                        yield self.finding(
                            node,
                            f"shard_map in_specs has {n} spec(s) but the "
                            f"wrapped function "
                            f"{getattr(fn_def, 'name', '<lambda>')!r} "
                            f"takes {want} positional argument(s) — the "
                            "mismatch only surfaces as a trace-time "
                            "pytree error on a real mesh")

            # ---- literal P(axis) names vs module Mesh axis names ----
            if mesh_axes:
                for spec in (in_specs, out_specs):
                    if spec is None:
                        continue
                    for axis, pnode in _spec_axis_literals(spec):
                        if axis not in mesh_axes:
                            yield self.finding(
                                pnode,
                                f"PartitionSpec names axis {axis!r} but "
                                f"the meshes constructed in this module "
                                f"define axes {sorted(mesh_axes)} — an "
                                "unbound axis shards nothing (or aborts) "
                                "at run time")
