"""TPS006 — Pallas kernel sanity.

Statically checkable invariants of ``pl.pallas_call`` sites:

* ``interpret=True`` left enabled — the interpreter escape hatch is for
  debugging; shipped call sites must thread it from a parameter (the
  repo's ``ops/pallas_stencil.py`` idiom) so production runs compile to
  Mosaic;
* grid/BlockSpec rank consistency — a ``BlockSpec`` index_map lambda must
  take exactly one index per grid dimension, and when its body is a tuple
  literal it must return one block coordinate per block-shape dimension.
  Rank mismatches otherwise surface as opaque Mosaic lowering errors.
"""

from __future__ import annotations

import ast

from ..context import terminal_name
from .base import Rule, register


def _grid_rank(node: ast.expr):
    """Statically-known grid rank, or None."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return len(node.elts)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return 1
    return None


def _iter_blockspecs(call: ast.Call):
    """All pl.BlockSpec(...) Call nodes in in_specs/out_specs kwargs."""
    for kw in call.keywords:
        if kw.arg not in ("in_specs", "out_specs"):
            continue
        for node in ast.walk(kw.value):
            if (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "BlockSpec"):
                yield node


@register
class PallasRule(Rule):
    id = "TPS006"
    name = "pallas-sanity"
    description = ("pallas_call with interpret=True left enabled, or "
                   "BlockSpec index_map arity/rank inconsistent with the "
                   "declared grid")

    def check(self, module):
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "pallas_call"):
                continue
            yield from self._check_interpret(node)
            yield from self._check_ranks(node)

    def _check_interpret(self, call: ast.Call):
        for kw in call.keywords:
            if (kw.arg == "interpret" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True):
                yield self.finding(
                    kw.value,
                    "`interpret=True` left enabled on a pallas_call — the "
                    "interpreter escape hatch must be threaded from a "
                    "parameter (default False) so shipped kernels compile "
                    "to Mosaic")

    def _check_ranks(self, call: ast.Call):
        grid = None
        for kw in call.keywords:
            if kw.arg == "grid":
                grid = _grid_rank(kw.value)
        for spec in _iter_blockspecs(call):
            block_shape = spec.args[0] if spec.args else None
            index_map = spec.args[1] if len(spec.args) > 1 else None
            for kw in spec.keywords:
                if kw.arg == "index_map":
                    index_map = kw.value
                elif kw.arg == "block_shape":
                    block_shape = kw.value
            if not isinstance(index_map, ast.Lambda):
                continue
            arity = len(index_map.args.args)
            if grid is not None and arity != grid:
                yield self.finding(
                    index_map,
                    f"BlockSpec index_map takes {arity} grid indices but "
                    f"the pallas_call grid has rank {grid} — one index per "
                    "grid dimension")
            if (isinstance(block_shape, (ast.Tuple, ast.List))
                    and isinstance(index_map.body, ast.Tuple)
                    and len(index_map.body.elts) != len(block_shape.elts)):
                yield self.finding(
                    index_map,
                    f"BlockSpec index_map returns "
                    f"{len(index_map.body.elts)} block coordinates for a "
                    f"rank-{len(block_shape.elts)} block_shape — ranks "
                    "must match")
