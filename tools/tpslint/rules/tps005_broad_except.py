"""TPS005 — broad exception swallowing around device/compile code.

``except Exception:`` (or bare ``except:``/``except BaseException:``)
around device placement, compilation, or collective code hides the
difference between "this dtype can't compile on this backend, fall back"
(expected, recoverable) and a genuine bug (shape mismatch, wrong axis
name) that should surface immediately.  Catch the narrow set a site can
actually raise — device/compile failures are ``RuntimeError`` (JAX's
``JaxRuntimeError``/``XlaRuntimeError`` both subclass it), trace-time
failures are ``TypeError``/``ValueError`` — or suppress with a
justification when catching everything is genuinely the contract
(classify-and-re-raise wrappers, user-callback isolation).
"""

from __future__ import annotations

import ast

from ..context import terminal_name
from .base import Rule, register

_BROAD = {"Exception", "BaseException"}


def _is_broad(node: ast.expr) -> bool:
    if node is None:
        return True                      # bare except:
    name = terminal_name(node)
    if name in _BROAD:
        return True
    if isinstance(node, ast.Tuple):
        return any(_is_broad(e) for e in node.elts)
    return False


@register
class BroadExceptRule(Rule):
    id = "TPS005"
    name = "broad-except"
    description = ("`except Exception:`/bare `except:` — catch the specific "
                   "exceptions the site can raise (device failures are "
                   "RuntimeError subclasses) or justify the suppression")

    def check(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node.type):
                what = ("bare `except:`" if node.type is None
                        else f"`except {ast.unparse(node.type)}:`")
                yield self.finding(
                    node,
                    f"{what} swallows unrelated bugs along with the "
                    "expected failure — narrow it (JAX device/compile "
                    "errors subclass RuntimeError; trace errors are "
                    "TypeError/ValueError) or suppress with justification")
