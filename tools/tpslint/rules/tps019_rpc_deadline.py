"""TPS019 — every RPC/transport wait must carry a deadline or timeout.

The multi-host transport (serving/transport.py + serving/remote.py) is
built on one invariant: NO call path blocks forever. The client divides
a per-call deadline across retry attempts, the host's duplicate-join
wait is bounded, and in-flight futures fail over after their deadline
instead of hanging. That invariant is only as strong as its weakest
call site — one ``client.call("solve", payload)`` without a budget
reintroduces the infinite hang the whole layer exists to remove, and it
reintroduces it silently: the code works until the first real host
loss.

This rule enforces the call-site half, lexically and per-function, in
the TPS018 taint style:

* **Direct blocking sources** — ``.call(...)`` / ``.call_once(...)`` /
  ``.send(...)`` / ``.recv(...)`` / ``.request(...)`` on a receiver
  whose terminal name contains an RPC fragment (``rpc`` / ``transport``
  / ``stub`` / ``remote`` / ``client``) must mention a budget among
  their arguments — a keyword named (or an argument expression
  mentioning) ``deadline`` / ``timeout`` / ``budget`` / ``remaining``.
  A bare blocking call is a finding at that call.
* **Future taint** — ``.submit(...)`` / ``.call_async(...)`` on an RPC
  receiver taints the assigned names (transitively, to a fixpoint); a
  ``.result()`` or ``.exception()`` on a tainted name with NO arguments
  is an unbounded wait on a network future — a finding. Any argument
  (positional or keyword) clears it: the stdlib signature's first
  parameter IS the timeout.

Like every tpslint rule this is conservative and syntactic: receivers
are matched by name fragment, taint does not flow through helper calls
or containers, and mentioning a budget name is trusted (the VALUE is
not checked — ``timeout=None`` is an explicit, greppable decision,
which is the point)."""

from __future__ import annotations

import ast

from ..context import FUNCTION_NODES, terminal_name
from .base import Rule, register

#: methods that BLOCK on the wire when invoked on an RPC-ish receiver
_BLOCKING_METHODS = frozenset({"call", "call_once", "send", "recv",
                               "request"})
#: methods that return a network-backed future (taint sources)
_ASYNC_METHODS = frozenset({"submit", "call_async"})
#: a receiver counts as RPC/transport when its terminal name contains
#: one of these fragments (rpc / _rpc / transport / stub / remote /
#: client / self.client ...)
_RECEIVER_FRAGMENTS = ("rpc", "transport", "stub", "remote", "client")
#: argument/keyword fragments that count as a blocking budget
_BUDGET_FRAGMENTS = ("deadline", "timeout", "budget", "remaining")
#: future methods that block unboundedly when called with no arguments
_WAIT_METHODS = frozenset({"result", "exception"})


def _rpc_receiver(node: ast.Call) -> bool:
    if not isinstance(node.func, ast.Attribute):
        return False
    recv = terminal_name(node.func.value)
    if recv is None:
        return False
    low = recv.lower()
    return any(f in low for f in _RECEIVER_FRAGMENTS)


def _is_blocking_call(node) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _BLOCKING_METHODS
            and _rpc_receiver(node))


def _is_async_source(node) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _ASYNC_METHODS
            and _rpc_receiver(node))


def _has_budget(node: ast.Call) -> bool:
    """A keyword named like a budget, or any argument expression that
    mentions one (``timeout=5``, ``deadline=d``, a positional
    ``remaining`` variable...)."""
    for kw in node.keywords:
        if kw.arg is not None:
            low = kw.arg.lower()
            if any(f in low for f in _BUDGET_FRAGMENTS):
                return True
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        for sub in ast.walk(arg):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name is not None:
                low = name.lower()
                if any(f in low for f in _BUDGET_FRAGMENTS):
                    return True
    return False


def _walk_local(func):
    """Walk a function's OWN body, not descending into nested function
    definitions (each gets analyzed as its own context)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, FUNCTION_NODES + (ast.ClassDef,)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _assign_name(target) -> str | None:
    while isinstance(target, (ast.Subscript, ast.Starred)):
        target = target.value
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def _contains_source_or_taint(node, tainted) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
        if _is_async_source(sub):
            return True
    return False


@register
class RpcDeadlineRule(Rule):
    id = "TPS019"
    name = "rpc-deadline"
    description = ("an RPC/transport call site may not issue a blocking "
                   "wait without a deadline or timeout argument — one "
                   "bare call reintroduces the infinite hang the "
                   "transport layer exists to remove")
    severity = "error"

    def check(self, module):
        for func in ast.walk(module.tree):
            if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(func)

    def _check_function(self, func):
        has_async_source = False
        for node in _walk_local(func):
            if _is_blocking_call(node) and not _has_budget(node):
                yield self.finding(
                    node,
                    f"blocking RPC call .{node.func.attr}(...) without "
                    "a deadline/timeout argument — pass the call budget "
                    "explicitly (deadline=/timeout=); an unbounded "
                    "transport wait hangs forever on the first lost "
                    "reply")
            if _is_async_source(node):
                has_async_source = True
        if not has_async_source:
            return
        # taint: names holding network-backed futures, to a fixpoint
        tainted = set()
        changed = True
        while changed:
            changed = False
            for node in _walk_local(func):
                if not isinstance(node, ast.Assign):
                    continue
                if not _contains_source_or_taint(node.value, tainted):
                    continue
                for tgt in node.targets:
                    name = _assign_name(tgt)
                    if name is not None and name not in tainted:
                        tainted.add(name)
                        changed = True
        for node in _walk_local(func):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _WAIT_METHODS
                    and not node.args and not node.keywords):
                continue
            recv = terminal_name(node.func.value)
            if recv is not None and recv in tainted:
                yield self.finding(
                    node,
                    f"unbounded .{node.func.attr}() on a network-backed "
                    f"future ({recv!r} came from an RPC submit) — pass "
                    "a timeout; a lost reply must fail the future over, "
                    "not hang it")
