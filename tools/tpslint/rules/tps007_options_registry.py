"""TPS007 — options-flag registry check (ROADMAP, deferred from the
initial rule set; landed alongside the -ksp_abft* flag family).

Every ``-ksp_*``/``-eps_*``/``-pc_*``/``-svd_*``/``-st_*``/
``-solve_server_*``/``-elastic_*`` flag read from
the options database (``utils/options.py`` getters: ``get``,
``get_string``, ``get_int``, ``get_real``, ``get_bool``, ``has``) must
appear in the documented ``utils/options.KNOWN_FLAGS`` registry: a typo'd
flag name parses, runs, and silently changes nothing — the configuration
the driver thought it applied never reached the solver (the options-DB
analog of TPS012's fault-point registry).

The registry is read by PARSING the options module's AST (the
``KNOWN_FLAGS`` dict literal's string keys) — tpslint stays stdlib-only.
Flag arguments are recognized both as plain string literals
(``opt.get_int("eps_nev", ...)``) and as the repo's prefix-concatenation
idiom (``opt.get_real(p + "ksp_rtol", ...)`` — the RIGHT operand of the
``+``). Dynamic keys and literals outside the solver-flag prefixes (e.g.
``log_view``) are out of scope and stay silent.
"""

from __future__ import annotations

import ast
import functools
import re
from pathlib import Path

from .base import Rule, register

#: options-database getter method names whose first argument is a flag key
_GETTERS = ("get", "get_string", "get_int", "get_real", "get_bool", "has")

#: flag-name shape the registry governs (solver-object prefixes, plus
#: the serving layer's -solve_server_* family, the fleet router's
#: -fleet_*/-qos_*/-autoscale_* families, the elastic degraded-mesh
#: recovery's -elastic_* family, the transport tier's -rpc_* family
#: (-fleet_transport_* rides the fleet prefix), and the -telemetry*
#: observability family — whose master switch is the bare flag
#: 'telemetry')
_FLAG_RE = re.compile(
    r"^((ksp|eps|pc|svd|st|solve_server|elastic|fleet|qos|autoscale"
    r"|multisplit|rpc)"
    r"_[a-z0-9_]+"
    r"|telemetry(_[a-z0-9_]+)?)$")

_OPTIONS_REL = Path("mpi_petsc4py_example_tpu") / "utils" / "options.py"


@functools.lru_cache(maxsize=1)
def registered_flags() -> frozenset:
    """String keys of ``utils/options.KNOWN_FLAGS``, parsed from the
    module's AST. Empty when the file (or the dict) cannot be found — the
    rule then has nothing to check against and stays silent (the
    coverage meta-test in tests/test_tpslint.py fails loudly instead)."""
    path = Path(__file__).resolve().parents[3] / _OPTIONS_REL
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return frozenset()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "KNOWN_FLAGS" not in targets:
            continue
        if isinstance(node.value, ast.Dict):
            return frozenset(
                key.value for key in node.value.keys
                if isinstance(key, ast.Constant) and isinstance(key.value,
                                                                str))
    return frozenset()


def _flag_literal(arg):
    """The flag-name literal of a getter's first argument, or None.

    Handles the two repo idioms: a plain string constant, and the
    options-prefix concatenation ``p + "ksp_rtol"`` (flag = the right
    operand). Anything else (a variable, an f-string) is dynamic and not
    statically checkable."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if (isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add)
            and isinstance(arg.right, ast.Constant)
            and isinstance(arg.right.value, str)):
        return arg.right.value
    return None


def flag_read_sites(tree):
    """Yield ``(flag_or_None, call_node)`` for every options-getter call
    in ``tree`` whose first argument looks like a solver flag."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _GETTERS
                and node.args):
            continue
        flag = _flag_literal(node.args[0])
        if flag is not None and _FLAG_RE.match(flag):
            yield flag, node


@register
class OptionsRegistryRule(Rule):
    id = "TPS007"
    name = "options-flag-registry"
    description = ("every -ksp_*/-eps_*/-pc_*/-svd_*/-st_* flag read from "
                   "the options DB must appear in utils/options."
                   "KNOWN_FLAGS — a typo'd flag silently changes nothing")

    def check(self, module):
        known = registered_flags()
        if not known:
            return
        for flag, node in flag_read_sites(module.tree):
            if flag not in known:
                yield self.finding(
                    node,
                    f"options flag {flag!r} is not registered in "
                    "utils/options.KNOWN_FLAGS — a typo here (or a "
                    "missing registry entry) makes the flag silently "
                    "inert; register it or fix the name")
