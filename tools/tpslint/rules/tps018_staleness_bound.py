"""TPS018 — staleness-bound discipline for stale-exchange reads.

The asynchronous multisplit tier (solvers/multisplit.py) reads neighbor
iterates from a stale-tolerant exchange buffer
(parallel/exchange.StaleExchange): reads NEVER block and may be
arbitrarily old.  That is fine for the relaxation itself — bounded
staleness still contracts — but it is catastrophic for CONVERGENCE
decisions: a stale per-block norm routinely undershoots the true
residual, so a solve that compares exchange-read data against a
tolerance declares victory on an iterate nobody ever assembled.  The
repo's contract (module docs of both files) is that convergence is
declared ONLY through the bounded-staleness machinery:

* :func:`parallel.exchange.check_staleness_bound` — the explicit bound
  check every convergence-feeding read must flow through, or
* :meth:`StaleExchange.consistent_cut` — the matching-version cut the
  supervisor assembles the residual check from.

This rule enforces the call-site half of that contract, lexically and
per-function: a function that (a) reads from a stale exchange
(``.read()`` / ``.read_all()`` / ``.latest()`` on a receiver whose name
contains ``exch``), and (b) lets a read-derived value flow into a
convergence decision — a comparison against a tolerance/target name, or
an assignment to a ``*converged*``/``*reason*`` name — must (c) also
call one of the sanitizers above in the same function.  Functions that
read the exchange for non-convergence purposes (assembling the stale
boundary for the next relaxation step) are untouched: only the
convergence-shaped sinks trigger.

Like every tpslint rule this is conservative and syntactic: taint does
not flow through helper calls or containers, and a sanitizer anywhere
in the function clears it (the resync/bound structure is not checked —
only that the author engaged the bounded-staleness machinery at all).
"""

from __future__ import annotations

import ast

from ..context import FUNCTION_NODES, terminal_name
from .base import Rule, register

#: exchange-read methods whose results are stale-tolerant data
_SOURCE_METHODS = frozenset({"read", "read_all", "latest"})
#: a receiver counts as a stale exchange when its terminal name contains
#: this fragment (exchange / exch / _exchange / self._exchange ...)
_RECEIVER_FRAGMENT = "exch"
#: calls that clear a function: the bounded-staleness check or the
#: consistent-cut assembly (either terminal spelling — function or
#: method)
_SANITIZERS = frozenset({"check_staleness_bound", "consistent_cut"})
#: name fragments that mark the comparison partner of a convergence
#: decision (rtol/atol/tol/target thresholds)
_TOL_FRAGMENTS = ("tol", "target", "threshold")
#: assignment-target fragments that mark a convergence outcome
_DECISION_FRAGMENTS = ("converg", "reason")


def _is_exchange_read(node) -> bool:
    """``<exch>.read(...)`` / ``.read_all(...)`` / ``.latest(...)``."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _SOURCE_METHODS):
        return False
    recv = terminal_name(node.func.value)
    return recv is not None and _RECEIVER_FRAGMENT in recv.lower()


def _walk_local(func):
    """Walk a function's OWN body, not descending into nested function
    definitions (each gets analyzed as its own context)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, FUNCTION_NODES + (ast.ClassDef,)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _mentions_fragment(node, fragments) -> bool:
    """Any Name/Attribute identifier in ``node`` containing one of the
    lowercase ``fragments``."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is not None:
            low = name.lower()
            if any(f in low for f in fragments):
                return True
    return False


def _assign_name(target) -> str | None:
    while isinstance(target, (ast.Subscript, ast.Starred)):
        target = target.value
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


@register
class StalenessBoundRule(Rule):
    id = "TPS018"
    name = "staleness-bound"
    description = ("stale-exchange reads feeding a convergence decision "
                   "must flow through check_staleness_bound() or "
                   "consistent_cut() — a stale local norm is never a "
                   "convergence basis")
    severity = "error"

    def check(self, module):
        for func in ast.walk(module.tree):
            if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(func)

    def _check_function(self, func):
        has_source = False
        sanitized = False
        for node in _walk_local(func):
            if isinstance(node, ast.Call):
                if terminal_name(node.func) in _SANITIZERS:
                    sanitized = True
                if _is_exchange_read(node):
                    has_source = True
        if sanitized or not has_source:
            return
        # taint: names assigned (transitively) from an exchange read,
        # grown to a fixpoint — source order is irrelevant
        tainted = set()
        changed = True
        while changed:
            changed = False
            for node in _walk_local(func):
                if not isinstance(node, ast.Assign):
                    continue
                if not _contains_source_or_taint(node.value, tainted):
                    continue
                for tgt in node.targets:
                    name = _assign_name(tgt)
                    if name is not None and name not in tainted:
                        tainted.add(name)
                        changed = True
        yield from self._sinks(func, tainted)

    def _sinks(self, func, tainted):
        for node in _walk_local(func):
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                t_side = any(_contains_source_or_taint(s, tainted)
                             for s in sides)
                tol_side = any(
                    _mentions_fragment(s, _TOL_FRAGMENTS) for s in sides)
                if t_side and tol_side:
                    yield self.finding(
                        node,
                        "convergence decision on a raw stale-exchange "
                        "read: the compared value derives from "
                        ".read()/.read_all()/.latest() with no "
                        "check_staleness_bound()/consistent_cut() in "
                        "this function — a stale local norm "
                        "undershoots the true residual; bound the "
                        "staleness or declare at a consistent cut")
            elif isinstance(node, ast.Assign):
                if not _contains_source_or_taint(node.value, tainted):
                    continue
                for tgt in node.targets:
                    name = _assign_name(tgt)
                    if name is None:
                        continue
                    low = name.lower()
                    if any(f in low for f in _DECISION_FRAGMENTS):
                        yield self.finding(
                            node,
                            f"convergence outcome {name!r} assigned "
                            "from a raw stale-exchange read with no "
                            "check_staleness_bound()/consistent_cut() "
                            "in this function — stale data is never a "
                            "convergence basis")


def _contains_source_or_taint(node, tainted) -> bool:
    """Does ``node``'s subtree hold an exchange read or a tainted name?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
        if _is_exchange_read(sub):
            return True
    return False
