"""TPS002 — recompile / trace-break hazards.

Python-level control flow on traced values (``if``/``while``/``assert``/
``for``), string formatting of traced values, and unhashable jit static
arguments.  Each either raises ``TracerBoolConversionError`` at trace time
or — worse — silently retraces per call, turning the repo's cached
one-compile-per-shape solver programs into a compile-per-solve treadmill
(see ``solvers/krylov.py`` ``_PROGRAM_CACHE``).
"""

from __future__ import annotations

import ast

from .base import Rule, register

_UNHASHABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set)


@register
class RecompileRule(Rule):
    id = "TPS002"
    name = "recompile-hazard"
    description = ("Python branching/iteration on traced values, f-strings "
                   "of traced values in jitted code, and unhashable jit "
                   "static args — trace errors or silent per-call retraces")

    def check(self, module):
        for ctx in module.contexts:
            for node in module.iter_own_nodes(ctx.node):
                yield from self._check_node(module, ctx, node)
        yield from self._check_static_args(module)

    def _check_node(self, module, ctx, node):
        if isinstance(node, (ast.If, ast.While)):
            if module.expr_tainted(node.test, ctx.tainted):
                kw = "if" if isinstance(node, ast.If) else "while"
                yield self.finding(
                    node,
                    f"Python `{kw}` on a traced value in `{ctx.name}` — "
                    "tracers have no concrete truth value; use `lax.cond`/"
                    "`jnp.where` (or `lax.while_loop` for loops)")
        elif isinstance(node, ast.Assert):
            if module.expr_tainted(node.test, ctx.tainted):
                yield self.finding(
                    node,
                    f"`assert` on a traced value in `{ctx.name}` — runs at "
                    "trace time only (or errors); use `checkify` or debug "
                    "callbacks for runtime checks")
        elif isinstance(node, ast.For):
            if module.expr_tainted(node.iter, ctx.tainted):
                yield self.finding(
                    node,
                    f"Python `for` over a traced value in `{ctx.name}` — "
                    "unrolls at trace time or errors; use `lax.scan`/"
                    "`lax.fori_loop`")
        elif isinstance(node, ast.JoinedStr):
            for part in node.values:
                if (isinstance(part, ast.FormattedValue)
                        and module.expr_tainted(part.value, ctx.tainted)):
                    yield self.finding(
                        node,
                        f"f-string formats a traced value in `{ctx.name}` — "
                        "concretizes at trace time; use `jax.debug.print` "
                        "with deferred formatting")
                    break
        elif isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Name) and func.id in ("str", "format",
                                                           "repr")
                    and node.args
                    and module.expr_tainted(node.args[0], ctx.tainted)):
                yield self.finding(
                    node,
                    f"`{func.id}()` of a traced value in `{ctx.name}` — "
                    "concretizes at trace time; use `jax.debug.print`")

    def _check_static_args(self, module):
        """jit static_argnames naming a parameter whose default is an
        unhashable literal — every call raises (or, with a dict-keyed cache
        workaround, retraces)."""
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            static = module._static_argnames(node)
            if not static:
                continue
            params = node.args.posonlyargs + node.args.args
            defaults = node.args.defaults
            offset = len(params) - len(defaults)
            for i, default in enumerate(defaults):
                pname = params[offset + i].arg
                if pname in static and isinstance(default,
                                                  _UNHASHABLE_DEFAULTS):
                    yield self.finding(
                        default,
                        f"static arg `{pname}` of `{node.name}` defaults to "
                        "an unhashable literal — jit static args must be "
                        "hashable; use a tuple/frozenset or None sentinel")
