"""TPS004 — dtype drift on device paths.

Device code must respect the ``TPU_SOLVE_NO_X64`` discipline: the working
dtype is threaded from the operator (``utils/dtypes.py``), and whether the
MXU fast path or the emulated-f64 path runs is decided by it.  A hard-coded
``np.float64`` scalar or ``dtype="float64"`` inside a traced context pins
the wide path (or errors when x64 is disabled) regardless of what the
solver was configured to do.  Host-side f64 (``host_dtype``) is idiomatic
and not flagged — the rule only fires inside traced contexts.
"""

from __future__ import annotations

import ast

from .base import Rule, register

_WIDE = {"float64", "complex128"}

#: precision-plan constructors/helpers (solvers/cg_plans.PrecisionPlan,
#: utils/dtypes): a wide dtype handed to one of these is an INTENTIONAL
#: plan-mediated choice — the plan object carries it as the reduce/storage
#: channel and the cast sites downstream (`v.astype(prec.reduce)`) thread
#: it from the plan, never from a literal. Calls to these names are
#: exempt; a bare `.astype(jnp.float64)` next to one still fires.
_PLAN_FUNCS = {"precision_plan", "PrecisionPlan", "reduce_dtype",
               "tolerance_dtype", "inner_precision_dtype"}


@register
class DtypeDriftRule(Rule):
    id = "TPS004"
    name = "dtype-drift"
    description = ("hard-coded float64/complex128 constants or dtype= "
                   "literals inside traced contexts — thread the dtype from "
                   "the operator so TPU_SOLVE_NO_X64 stays in charge")

    def check(self, module):
        for ctx in module.contexts:
            for node in module.iter_own_nodes(ctx.node):
                if not isinstance(node, ast.Call):
                    continue
                yield from self._check_call(module, ctx, node)

    def _wide_dtype_expr(self, module, node: ast.expr) -> bool:
        """``np.float64`` / ``jnp.complex128`` attribute or a "float64"
        string — the spellings of a hard-coded wide dtype."""
        if (isinstance(node, ast.Attribute) and node.attr in _WIDE
                and (module.info.is_numpy_attr(node)
                     or module.info.is_jnp_attr(node))):
            return True
        return (isinstance(node, ast.Constant)
                and isinstance(node.value, str) and node.value in _WIDE)

    @staticmethod
    def _is_plan_call(func) -> bool:
        """``precision_plan(...)`` / ``PrecisionPlan(...)`` /
        ``dtypes.reduce_dtype(...)`` — by bare name or attribute."""
        if isinstance(func, ast.Name):
            return func.id in _PLAN_FUNCS
        if isinstance(func, ast.Attribute):
            return func.attr in _PLAN_FUNCS
        return False

    def _check_call(self, module, ctx, call: ast.Call):
        func = call.func
        if self._is_plan_call(func):
            # plan-mediated precision choice: the wide dtype is the
            # plan's declared reduce/storage channel, not drift
            return
        # np.float64(x) / jnp.complex128(x) scalar constructors
        if ((module.info.is_numpy_attr(func) or module.info.is_jnp_attr(func))
                and func.attr in _WIDE):
            yield self.finding(
                call,
                f"`{ast.unparse(func)}()` constant inside traced context "
                f"`{ctx.name}` pins the wide-dtype path — thread the dtype "
                "from the operand (utils/dtypes.py) instead")
            return
        # x.astype(np.float64) / x.astype("float64")
        if (isinstance(func, ast.Attribute) and func.attr == "astype"
                and call.args
                and self._wide_dtype_expr(module, call.args[0])):
            yield self.finding(
                call,
                f"`.astype({ast.unparse(call.args[0])})` inside traced "
                f"context `{ctx.name}` pins the wide-dtype path — must "
                "respect TPU_SOLVE_NO_X64; derive the dtype from the input")
            return
        # dtype=np.float64 keyword, or np.float64 passed positionally
        # (jnp.zeros(shape, jnp.float64) — the dtype slot of creation calls)
        hits = [kw.value for kw in call.keywords if kw.arg == "dtype"]
        hits.extend(a for a in call.args
                    if isinstance(a, ast.Attribute))
        for v in hits:
            if self._wide_dtype_expr(module, v):
                yield self.finding(
                    call,
                    f"`{ast.unparse(v)}` dtype hard-coded inside traced "
                    f"context `{ctx.name}` — must respect TPU_SOLVE_NO_X64; "
                    "derive the dtype from the input array")
                return
