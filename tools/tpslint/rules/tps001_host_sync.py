"""TPS001 — host sync inside a traced program.

``float()`` / ``int()`` / ``.item()`` / ``np.*()`` / ``.block_until_ready()``
applied to a traced value inside a jit/``lax`` control-flow/``shard_map``
context forces device->host materialization.  Inside ``jax.jit`` that is a
trace-time concretization error at best; inside a ``while_loop``/``scan``
body it is the exact bug class that silently breaks the repo's
one-XLA-program-per-solve guarantee (README "One XLA program per solve") and
shows up only as a mysterious per-iteration sync on an 8-device mesh.
"""

from __future__ import annotations

import ast

from .base import Rule, register

_SCALAR_CASTS = {"float", "int", "bool", "complex"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host_async"}


@register
class HostSyncRule(Rule):
    id = "TPS001"
    name = "host-sync-in-program"
    description = ("float()/int()/.item()/np.*/.block_until_ready() on a "
                   "traced value inside jit, lax control-flow bodies, or "
                   "shard_map — breaks the one-XLA-program-per-solve "
                   "guarantee")

    def check(self, module):
        for ctx in module.contexts:
            for node in module.iter_own_nodes(ctx.node):
                if not isinstance(node, ast.Call):
                    continue
                yield from self._check_call(module, ctx, node)

    def _check_call(self, module, ctx, call: ast.Call):
        func = call.func
        # float(x) / int(x) / bool(x) / complex(x) on a traced value
        if (isinstance(func, ast.Name) and func.id in _SCALAR_CASTS
                and call.args
                and module.expr_tainted(call.args[0], ctx.tainted)):
            yield self.finding(
                call,
                f"`{func.id}()` of a traced value inside "
                f"{self._where(ctx)} forces a device->host sync; return "
                "the array and materialize outside the compiled program")
            return
        # x.item() / x.tolist() / x.block_until_ready()
        if (isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS
                and module.expr_tainted(func.value, ctx.tainted)):
            yield self.finding(
                call,
                f"`.{func.attr}()` on a traced value inside "
                f"{self._where(ctx)} forces a device->host sync; hoist it "
                "out of the traced scope")
            return
        # np.anything(traced) — numpy concretizes its inputs
        if (module.info.is_numpy_attr(func)
                and any(module.expr_tainted(a, ctx.tainted)
                        for a in call.args)):
            yield self.finding(
                call,
                f"`{ast.unparse(func)}()` on a traced value inside "
                f"{self._where(ctx)} concretizes through host numpy; use "
                "the jnp equivalent so the op stays in the XLA program")

    @staticmethod
    def _where(ctx) -> str:
        if ctx.reason == "enclosing":
            return f"a function nested in a traced context (`{ctx.name}`)"
        return f"a `{ctx.reason}` context (`{ctx.name}`)"
