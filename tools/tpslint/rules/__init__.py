"""Rule registry — importing this package registers all built-in rules."""

from .base import Rule, register, registry
from . import (tps001_host_sync, tps002_recompile, tps003_axis_name,
               tps004_dtype_drift, tps005_broad_except, tps006_pallas,
               tps007_options_registry, tps008_interproc_sync,
               tps009_sharding, tps010_grid_spec, tps011_psum_fusion,
               tps012_fault_registry, tps013_donation, tps014_telemetry,
               tps015_dispatch_loop, tps016_lock_order, tps017_channel_mix,
               tps018_staleness_bound, tps019_rpc_deadline)


def all_rules() -> dict:
    """Rule-id -> rule instance, sorted by id."""
    return dict(sorted(registry().items()))


__all__ = ["Rule", "register", "registry", "all_rules"]
