"""TPS015 — dispatch-in-host-loop advisory (warn tier).

A compiled-program launch costs a fixed host->device dispatch latency
(~100 ms through the remote-TPU tunnel, BENCH_r05) that no amount of
on-chip speed amortizes.  A HOST-side ``for``/``while`` whose body
launches a compiled program per iteration multiplies that latency by the
trip count — the exact pathology the fused megasolve programs
(solvers/megasolve.py) remove by moving the outer recurrence into the
device program as a ``lax.while_loop``.

The check: for every host-side loop (a ``for``/``while`` statement not
inside a traced jit/shard_map/pallas context), look at each call in its
body and flag the loop when the call either

* invokes a compiled program DIRECTLY — the called name's reaching-defs
  provenance is a ``build_*program*`` factory call (``prog = \
build_ksp_program(...)`` ... ``prog(...)`` in a loop), or
* resolves through the :class:`~tools.tpslint.program.ProgramIndex`
  call graph to a function that TRANSITIVELY performs such an
  invocation (``self.solve(...)`` -> ``KSP._solve_impl`` ->
  ``prog(...)``), including one attribute hop through a ``self.<attr> =
  Class(...)`` constructor assignment (``self.inner.solve(...)`` — the
  RefinedKSP outer-loop shape).

Advisory only (``severity = "warn"``): some host loops over dispatches
are legitimate — retry/escalation ladders re-dispatch by design, chunked
``-ksp_batch_limit`` launches exist to fit VMEM, and the unfused
fallback paths remain load-bearing for configurations megasolve does not
cover.  The CI ``--warn-budget`` pins the COUNT of such sites so new
host-driven outer loops are a conscious choice (route through
``-ksp_megasolve`` where a fused program exists).  Dynamic callees the
index cannot resolve stay silent, like TPS008.
"""

from __future__ import annotations

import ast

from ..context import FUNCTION_NODES, qualifier_chain, terminal_name
from .base import Rule, register

#: compiled-program factory spellings: the explicit set plus the
#: build_*program* naming convention (krylov/megasolve/eps builders)
_BUILDER_NAMES = frozenset({
    "build_ksp_program", "build_ksp_program_many",
    "build_megasolve_program", "build_megasolve_program_many",
})


def _is_builder(func_expr) -> bool:
    name = terminal_name(func_expr)
    if name is None:
        return False
    return (name in _BUILDER_NAMES
            or (name.lstrip("_").startswith("build_")
                and "program" in name))


def _shallow_calls(nodes):
    """Every Call under ``nodes`` excluding nested def/class bodies
    (their calls run when THEY are called, not per loop iteration)."""
    stack = list(nodes)
    while stack:
        n = stack.pop()
        if isinstance(n, FUNCTION_NODES + (ast.ClassDef,)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _invokes_program(index, module, call) -> bool:
    """Does this call site execute a compiled program? Either the
    immediate ``build_*program*(...)(args)`` shape or a name whose
    reaching-defs provenance is a builder call."""
    f = call.func
    if isinstance(f, ast.Call):
        return _is_builder(f.func)
    if isinstance(f, ast.Name):
        val = index.resolve_local_value(module, f)
        return isinstance(val, ast.Call) and _is_builder(val.func)
    return False


def _resolve(index, module, call):
    """``index.resolve_call`` plus ONE attribute hop for
    ``self.<attr>.method(...)`` where ``self.<attr> = Class(...)`` is
    assigned in the enclosing class (the RefinedKSP ``self.inner.solve``
    shape) — conservative: a unique constructor assignment only."""
    rec = index.resolve_call(module, call)
    if rec is not None:
        return rec
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    chain = qualifier_chain(func)
    if not (chain and len(chain) == 2 and chain[0] in ("self", "cls")):
        return None
    cls_node = index._enclosing_class(module, call)
    entry = index.module_for(module.path)
    if cls_node is None or entry is None:
        return None
    ctor_names = set()
    for n in ast.walk(cls_node):
        if not (isinstance(n, ast.Assign)
                and isinstance(n.value, ast.Call)):
            continue
        for t in n.targets:
            if (isinstance(t, ast.Attribute) and t.attr == chain[1]
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                cname = terminal_name(n.value.func)
                if cname is not None:
                    ctor_names.add(cname)
    if len(ctor_names) != 1:
        return None                   # ambiguous/dynamic attribute
    cname = ctor_names.pop()
    rec = entry.symbols.get(f"{cname}.{func.attr}")
    if rec is not None:
        return rec
    imp = entry.imports.get(cname)
    if imp is None:
        return None
    base, sym = imp
    if sym is None:
        return None
    target = index._lookup_module(base)
    if target is None:
        return None
    return target.symbols.get(f"{sym}.{func.attr}")


def _dispatch_chain(index, rec, stack):
    """``None`` or the hop list down to a compiled-program invocation,
    memoized on the index (source-coordinate keys, like the TPS008 sync
    summaries)."""
    memo = index.__dict__.setdefault("_tps015_memo", {})
    key = index._node_key(rec)
    if key in memo:
        return memo[key]
    if key in stack:
        return None                   # cycle: judged by the other hops
    stack = stack | {key}
    module = rec.entry.analysis
    result = None
    for call in _shallow_calls(rec.node.body):
        if _invokes_program(index, module, call):
            result = [f"`{rec.qualname}` ({rec.path}:{call.lineno}) "
                      "invokes a compiled program"]
            break
        callee = _resolve(index, module, call)
        if callee is None or callee.node is rec.node:
            continue
        sub = _dispatch_chain(index, callee, stack)
        if sub is not None:
            result = ([f"`{rec.qualname}` ({rec.path}:{call.lineno}) "
                       f"calls `{callee.qualname}`"] + sub)
            break
    memo[key] = result
    return result


@register
class DispatchInHostLoopRule(Rule):
    id = "TPS015"
    name = "dispatch-in-host-loop"
    description = ("a host-side for/while loop whose body launches a "
                   "compiled program each iteration (directly or through "
                   "the call graph) — per-iteration dispatch latency the "
                   "fused megasolve programs exist to remove")
    severity = "warn"

    def check(self, module):
        index = module.program
        if index is None:
            return
        traced = {id(ctx.node) for ctx in module.contexts}
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue
            if self._in_traced(module, node, traced):
                continue
            body = list(node.body) + list(node.orelse)
            for call in _shallow_calls(body):
                chain = None
                if _invokes_program(index, module, call):
                    chain = ["the loop body invokes the compiled "
                             "program directly"]
                else:
                    callee = _resolve(index, module, call)
                    if callee is not None:
                        chain = _dispatch_chain(index, callee, set())
                if chain is not None:
                    yield self.finding(
                        node,
                        "host-side loop dispatches a compiled program "
                        f"per iteration (line {call.lineno}: "
                        f"`{ast.unparse(call.func)}`) — "
                        + " -> ".join(chain) +
                        "; per-iteration launch latency multiplies by "
                        "the trip count — fuse the recurrence into the "
                        "device program (-ksp_megasolve / "
                        "lax.while_loop) where a fused form exists")
                    break             # one finding per loop

    @staticmethod
    def _in_traced(module, node, traced) -> bool:
        cur = node
        while cur is not None:
            if id(cur) in traced:
                return True
            cur = module.parents.get(cur)
        return False
