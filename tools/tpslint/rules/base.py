"""Rule base class and registry."""

from __future__ import annotations

from ..findings import Finding

_REGISTRY = {}


def register(cls):
    """Class decorator adding a Rule to the global registry."""
    inst = cls()
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id}")
    _REGISTRY[inst.id] = inst
    return cls


def registry() -> dict:
    return dict(_REGISTRY)


class Rule:
    """One check.  Subclasses set ``id``/``name``/``description`` and
    implement :meth:`check` yielding :class:`Finding` objects."""

    id = "TPS999"
    name = "unnamed"
    #: One-line rationale shown by ``tpslint --list-rules``.
    description = ""
    #: "error" fails the lint; "warn" is the advisory tier (counted
    #: against the CI --warn-budget, never a failure by itself).
    severity = "error"

    def check(self, module):
        """Yield findings for a :class:`~tools.tpslint.context.ModuleAnalysis`."""
        raise NotImplementedError

    def finding(self, node, message: str) -> Finding:
        return Finding(rule=self.id, message=message,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       severity=self.severity)
