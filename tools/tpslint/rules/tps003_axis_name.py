"""TPS003 — hard-coded collective axis names.

Every collective must name the mesh axis the ``DeviceComm`` actually
created (``parallel/mesh.py``, ``ROW_AXIS``).  A string literal at a
``lax.psum``/``all_gather``/``ppermute`` call site works until someone
builds a mesh with a different axis name (2-D meshes, tests with private
meshes) and then fails at runtime on an 8-device mesh with an unbound-axis
error — or, worse, silently reduces over the wrong axis of a 2-D mesh.
Thread the name from ``DeviceComm.axis`` (or a parameter fed from it).
"""

from __future__ import annotations

import ast

from ..context import terminal_name
from .base import Rule, register

#: collective terminal name -> positional index of the axis-name argument
COLLECTIVE_AXIS_ARG = {
    "psum": 1,
    "pmax": 1,
    "pmin": 1,
    "pmean": 1,
    "all_gather": 1,
    "ppermute": 1,
    "pshuffle": 1,
    "pswapaxes": 1,
    "all_to_all": 1,
    "psum_scatter": 1,
    "axis_index": 0,
    "axis_size": 0,
}


def _is_string_literal(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_is_string_literal(e) for e in node.elts)
    if isinstance(node, ast.JoinedStr):
        # f-strings hard-code the axis just as surely as a plain literal:
        # f"rows" and f"rows_{i}" both carry literal text (Constant parts
        # or a literal inside a FormattedValue); only a PURE interpolation
        # of a threaded name — f"{comm.axis}" — is dynamic
        for part in node.values:
            if (isinstance(part, ast.Constant)
                    and isinstance(part.value, str) and part.value):
                return True
            if (isinstance(part, ast.FormattedValue)
                    and _is_string_literal(part.value)):
                return True
    return False


@register
class AxisNameRule(Rule):
    id = "TPS003"
    name = "hard-coded-axis-name"
    description = ("lax.psum/all_gather/ppermute/axis_index axis names must "
                   "be threaded from DeviceComm.axis, never string literals")

    def check(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            if name not in COLLECTIVE_AXIS_ARG:
                continue
            axis_arg = None
            idx = COLLECTIVE_AXIS_ARG[name]
            if idx < len(node.args):
                axis_arg = node.args[idx]
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis_arg = kw.value
            if axis_arg is not None and _is_string_literal(axis_arg):
                yield self.finding(
                    node,
                    f"`{name}` called with a hard-coded axis name "
                    f"{ast.unparse(axis_arg)!s} — thread the axis from "
                    "`DeviceComm.axis` (parallel/mesh.py) so private/2-D "
                    "meshes keep working")
