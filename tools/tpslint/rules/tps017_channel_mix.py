"""TPS017 — precision-channel mixing advisory (warn tier).

A :class:`~mpi_petsc4py_example_tpu.solvers.cg_plans.PrecisionPlan`
splits a solve into two dtype channels: ``storage`` (what the iterate
vectors, gathers and halos move — bf16 under the mixed plans) and
``reduce`` (the dot-product/norm/ABFT accumulation channel, kept
wider).  The channel boundary is crossed ONLY through the plan's own
hooks — ``plan.up(v)`` lifts into the reduce channel, ``plan.store(v)``
casts back — so the lowered program's reduce-channel dtype is exactly
what the plan declares (the property the TPC005 contract pin and the
collective-byte budgets rest on).

This rule flags arithmetic that mixes a storage-channel value into a
reduce-channel value DIRECTLY: ``ru + p`` where ``ru = up(r)`` and
``p = plan.store(p0)`` promotes through jnp's implicit type promotion
instead of the plan — the result dtype is whatever the promotion
lattice says, not what the plan declares, and the drift surfaces three
layers up as a contract/volume-gate failure.  The fix is always to
route the operand through the plan (``up(p)``, or move the mix inside
the ``store(...)`` argument, where the cast-back makes the promotion
intentional — that spelling is exempt).

Value provenance is one assignment deep (names assigned from
``up(...)``/``store(...)``/``.astype(plan.storage)`` calls, including
the ``_up = prec.up`` aliasing idiom and tuple-unpacked casts); plan
objects are recognized by TPS004's ``_PLAN_FUNCS`` constructor set
plus the canonical ``prec``/``plan`` parameter names.  Deeper flow is
invisible — conservative, like TPS008.  Advisory tier: uniform-
precision plans make every hook the identity, so a flagged mix is only
WRONG under a mixed plan the call site may never see; the warn budget
makes each one a conscious choice.
"""

from __future__ import annotations

import ast

from ..context import FUNCTION_NODES
from .base import Rule, register
from .tps004_dtype_drift import _PLAN_FUNCS

#: canonical plan-object parameter spellings in the solver kernels
_PLAN_PARAM_NAMES = frozenset({"prec", "plan", "pplan", "precision"})

_CHANNEL_BY_HOOK = {"up": "reduce", "store": "storage"}
_CHANNEL_BY_ATTR = {"reduce": "reduce", "storage": "storage"}


def _is_top_level_function(module, func) -> bool:
    node = module.parents.get(func)
    while node is not None:
        if isinstance(node, FUNCTION_NODES):
            return False
        node = module.parents.get(node)
    return True


class _Scope:
    """One closure's channel facts: plan names, caster aliases
    (``_up = prec.up``), and channel-tagged value names."""

    def __init__(self, func):
        self.plans = set()
        self.casters = {}           # alias name -> "up" | "store"
        self.tags = {}              # value name -> "reduce" | "storage"
        args = getattr(func, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg in _PLAN_PARAM_NAMES:
                    self.plans.add(a.arg)
        self._collect(func)

    # ------------------------------------------------------------- helpers
    def _plan_hook(self, node) -> str | None:
        """``"up"``/``"store"`` for a ``<plan>.up`` attribute expr."""
        if (isinstance(node, ast.Attribute)
                and node.attr in _CHANNEL_BY_HOOK
                and isinstance(node.value, ast.Name)
                and node.value.id in self.plans):
            return node.attr
        return None

    def _hook_in_expr(self, node) -> str | None:
        """A plan hook possibly wrapped in the conditional-identity
        idiom ``up = (prec.up if prec.mixed else (lambda v: v))``."""
        hook = self._plan_hook(node)
        if hook is not None:
            return hook
        if isinstance(node, ast.IfExp):
            return (self._hook_in_expr(node.body)
                    or self._hook_in_expr(node.orelse))
        return None

    def call_channel(self, node) -> str | None:
        """The channel a value expression lands in, or None: a call
        through a plan hook / caster alias, or ``.astype(plan.<chan>)``."""
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        hook = self._plan_hook(f)
        if hook is not None:
            return _CHANNEL_BY_HOOK[hook]
        if isinstance(f, ast.Name) and f.id in self.casters:
            return _CHANNEL_BY_HOOK[self.casters[f.id]]
        if (isinstance(f, ast.Attribute) and f.attr == "astype"
                and node.args):
            arg = node.args[0]
            if (isinstance(arg, ast.Attribute)
                    and arg.attr in _CHANNEL_BY_ATTR
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id in self.plans):
                return _CHANNEL_BY_ATTR[arg.attr]
        return None

    @staticmethod
    def _is_plan_ctor(node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        return name in _PLAN_FUNCS

    # ------------------------------------------------------------ collection
    def _collect(self, func):
        for node in ast.walk(func):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt, val = node.targets[0], node.value
            pairs = []
            if isinstance(tgt, ast.Name):
                pairs = [(tgt, val)]
            elif (isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple)
                  and len(tgt.elts) == len(val.elts)):
                pairs = [(t, v) for t, v in zip(tgt.elts, val.elts)
                         if isinstance(t, ast.Name)]
            for t, v in pairs:
                if self._is_plan_ctor(v):
                    self.plans.add(t.id)
                    continue
                hook = self._hook_in_expr(v)
                if hook is not None:
                    self.casters[t.id] = hook
                    continue
                chan = self.call_channel(v)
                if chan is not None:
                    self.tags[t.id] = chan


@register
class ChannelMixRule(Rule):
    id = "TPS017"
    name = "channel-mix"
    description = ("arithmetic mixing a PrecisionPlan storage-channel "
                   "value into the reduce channel without a plan-"
                   "mediated cast — implicit promotion decides the "
                   "dtype, not the plan")
    severity = "warn"

    def check(self, module):
        for func in ast.walk(module.tree):
            if not isinstance(func, FUNCTION_NODES):
                continue
            if not _is_top_level_function(module, func):
                continue
            scope = _Scope(func)
            if not (scope.plans or scope.casters):
                continue
            yield from self._check_scope(module, func, scope)

    def _check_scope(self, module, func, scope):
        for node in ast.walk(func):
            if not isinstance(node, ast.BinOp):
                continue
            chans = {}
            for side in (node.left, node.right):
                if isinstance(side, ast.Name) and side.id in scope.tags:
                    chans[scope.tags[side.id]] = side.id
            if len(chans) < 2:
                continue
            if self._plan_mediated(module, scope, node):
                continue
            yield self.finding(
                node,
                f"`{chans['storage']}` (storage channel) mixed into "
                f"`{chans['reduce']}` (reduce channel) by bare "
                f"arithmetic — implicit promotion, not the plan, "
                f"decides the result dtype; lift the operand with the "
                f"plan's up()/store() hooks instead")

    def _plan_mediated(self, module, scope, node) -> bool:
        """Is this expression inside an argument to a plan hook / caster
        call (``store(x + alpha * p)`` — the documented idiom)?"""
        cur = module.parents.get(node)
        while cur is not None and not isinstance(
                cur, FUNCTION_NODES + (ast.stmt,)):
            if (isinstance(cur, ast.Call)
                    and (scope.call_channel(cur) is not None
                         or scope._is_plan_ctor(cur))):
                return True
            cur = module.parents.get(cur)
        return False
