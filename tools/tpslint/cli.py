"""``tpslint`` console entry point.

Usage::

    tpslint mpi_petsc4py_example_tpu/ compat/ tools/ examples/
    tpslint --strict ...          # CI mode: also fail on unused suppressions
    tpslint --list-rules
    tpslint --select TPS001,TPS005 path/
    tpslint --sarif out.sarif ...             # GitHub code-scanning log
    tpslint ... --changed-files a.py dir/     # full index, filtered report
    tpslint --index-cache .cache/idx ...      # reuse the phase-1 parse

Two-phase (round 9): every run first builds the project-wide program
index over ALL given paths (module/symbol table + call graph — what the
interprocedural rules TPS008/TPS013 walk), then lints.  The
``--changed-files`` PR mode keeps the full index but reports findings
only in the listed files; ``--index-cache`` persists the phase-1 parse
keyed on a source-tree hash so repeated subdir runs in one CI workflow
parse the tree once.
"""

from __future__ import annotations

import argparse
import os
import sys

from .cache import load_index, save_index, tree_hash
from .engine import analyze_paths, build_index
from .rules import all_rules
from .sarif import write_sarif


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpslint",
        description=("JAX/TPU-aware static analysis guarding the "
                     "jit/shard_map/Pallas invariants of the TPU "
                     "sparse-solve stack"))
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint (and to index — "
                        "the interprocedural rules see all of them)")
    p.add_argument("--strict", action="store_true",
                   help="also fail on unused (stale) suppressions")
    p.add_argument("--warn-budget", type=int, default=None,
                   metavar="N",
                   help="fail when warn-tier (advisory) findings exceed N "
                        "(default: warnings never fail — the CI passes the "
                        "current count so advisories cannot silently "
                        "accumulate)")
    p.add_argument("--select", default=None, metavar="TPS001,TPS002",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--changed-files", nargs="+", default=None,
                   metavar="PATH",
                   help="report findings only in these files/directories; "
                        "the program index still covers every positional "
                        "path, so cross-file analysis stays whole-program "
                        "(the fast PR-lint mode). Non-Python and deleted "
                        "paths are ignored; listed files outside the "
                        "indexed paths are skipped with a note")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="also write findings as a SARIF 2.1.0 log "
                        "(GitHub code-scanning annotations)")
    p.add_argument("--index-cache", default=None, metavar="PATH",
                   help="pickle the phase-1 program index here, keyed on "
                        "a source-tree hash; a matching cache skips "
                        "re-parsing (CI: key the cache on the tree hash "
                        "so subdir lint steps share one parse)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by justified "
                        "suppressions")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rid, rule in all_rules().items():
            print(f"{rid}  {rule.name}")
            print(f"        {rule.description}")
        return 0

    if not args.paths:
        print("tpslint: error: no paths given (try --list-rules, or pass "
              "package directories)", file=sys.stderr)
        return 2

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must not lint zero files and report "clean"
        print(f"tpslint: error: no such file or directory: "
              f"{', '.join(missing)}", file=sys.stderr)
        return 2

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = set(select) - set(all_rules())
        if unknown:
            print(f"tpslint: error: unknown rule(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    # ---- phase 1: program index (cached when --index-cache hits) ----
    index = phase1_errors = None
    cache_key = None
    if args.index_cache:
        cache_key = tree_hash(args.paths)
        hit = load_index(args.index_cache, cache_key)
        if hit is not None:
            index, phase1_errors = hit
    if index is None:
        index, phase1_errors = build_index(args.paths)
        if args.index_cache:
            # precompute the expensive interprocedural summaries so the
            # cached index carries them — a cache hit skips the whole
            # phase-1 cost, not just the parse
            index.sync_summaries()
            save_index(args.index_cache, cache_key, index, phase1_errors)

    # ---- changed-files scope: full index, filtered report ----
    report_files = None
    if args.changed_files is not None:
        indexed = set(index.modules)
        # a file missing from the index is not necessarily out of scope:
        # unreadable/unparsable files are skipped by phase 1 but carry a
        # TPS-READ/TPS-PARSE finding that must still fail a PR touching them
        erred = {os.path.normpath(e.path) for e in phase1_errors}
        report_files = []
        for path in args.changed_files:
            if os.path.isdir(path):
                report_files.append(path)
            elif not path.endswith(".py") or not os.path.exists(path):
                continue        # deleted/non-Python changes: nothing to lint
            elif os.path.normpath(path) in indexed \
                    or os.path.normpath(path) in erred:
                report_files.append(path)
            else:
                print(f"tpslint: note: {path} is outside the linted "
                      "paths; skipping", file=sys.stderr)
        if not report_files:
            print("tpslint: clean (no changed Python files under the "
                  "linted paths)", file=sys.stderr)
            if args.sarif:
                empty = analyze_paths([], index=index, report_files=[])
                write_sarif(args.sarif, empty, all_rules(),
                            base_dir=os.getcwd())
            return 0

    result = analyze_paths(args.paths, select=select, index=index,
                           report_files=report_files)
    if report_files is None:
        result.errors.extend(phase1_errors)
    else:
        rset = _report_set(report_files)
        result.errors.extend(e for e in phase1_errors
                             if os.path.normpath(e.path) in rset)

    if args.sarif:
        write_sarif(args.sarif, result, all_rules(), base_dir=os.getcwd())

    for f in result.errors:
        print(f.format())
    for f in result.findings:
        print(f.format())
    for f in result.warnings:
        print(f.format())
    for f in result.bad_suppressions:
        print(f.format())
    if args.show_suppressed:
        for f, s in result.suppressed:
            print(f"{f.format()}  [suppressed: {s.justification}]")
    if args.strict:
        for s in result.unused_suppressions:
            print(f"{s.path}:{s.line}:0: TPS000 unused suppression of "
                  f"{', '.join(s.rules)} (nothing fires on the guarded "
                  "line)")

    n = len(result.findings) + len(result.bad_suppressions) + \
        len(result.errors)
    nw = len(result.warnings)
    code = result.exit_code(strict=args.strict,
                            warn_budget=args.warn_budget)
    if n or nw or (args.strict and result.unused_suppressions):
        extra = (f", {len(result.unused_suppressions)} unused "
                 "suppression(s)" if args.strict
                 and result.unused_suppressions else "")
        warn = ""
        if nw:
            budget = ("no budget" if args.warn_budget is None
                      else f"budget {args.warn_budget}")
            warn = f", {nw} warning(s) ({budget})"
        print(f"tpslint: {n} finding(s){warn}{extra}", file=sys.stderr)
    elif result.suppressed:
        print(f"tpslint: clean ({len(result.suppressed)} justified "
              "suppression(s))", file=sys.stderr)
    else:
        print("tpslint: clean", file=sys.stderr)
    return code


def _report_set(report_files):
    from .engine import iter_python_files
    return {os.path.normpath(f) for f in iter_python_files(report_files)}


if __name__ == "__main__":
    sys.exit(main())
