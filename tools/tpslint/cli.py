"""``tpslint`` console entry point.

Usage::

    tpslint mpi_petsc4py_example_tpu/ compat/ tools/ examples/
    tpslint --strict ...          # CI mode: also fail on unused suppressions
    tpslint --list-rules
    tpslint --select TPS001,TPS005 path/
"""

from __future__ import annotations

import argparse
import os
import sys

from .engine import analyze_paths
from .rules import all_rules


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpslint",
        description=("JAX/TPU-aware static analysis guarding the "
                     "jit/shard_map/Pallas invariants of the TPU "
                     "sparse-solve stack"))
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint")
    p.add_argument("--strict", action="store_true",
                   help="also fail on unused (stale) suppressions")
    p.add_argument("--warn-budget", type=int, default=None,
                   metavar="N",
                   help="fail when warn-tier (advisory) findings exceed N "
                        "(default: warnings never fail — the CI passes the "
                        "current count so advisories cannot silently "
                        "accumulate)")
    p.add_argument("--select", default=None, metavar="TPS001,TPS002",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by justified "
                        "suppressions")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rid, rule in all_rules().items():
            print(f"{rid}  {rule.name}")
            print(f"        {rule.description}")
        return 0

    if not args.paths:
        print("tpslint: error: no paths given (try --list-rules, or pass "
              "package directories)", file=sys.stderr)
        return 2

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must not lint zero files and report "clean"
        print(f"tpslint: error: no such file or directory: "
              f"{', '.join(missing)}", file=sys.stderr)
        return 2

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = set(select) - set(all_rules())
        if unknown:
            print(f"tpslint: error: unknown rule(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    result = analyze_paths(args.paths, select=select)

    for f in result.errors:
        print(f.format())
    for f in result.findings:
        print(f.format())
    for f in result.warnings:
        print(f.format())
    for f in result.bad_suppressions:
        print(f.format())
    if args.show_suppressed:
        for f, s in result.suppressed:
            print(f"{f.format()}  [suppressed: {s.justification}]")
    if args.strict:
        for s in result.unused_suppressions:
            print(f"{s.path}:{s.line}:0: TPS000 unused suppression of "
                  f"{', '.join(s.rules)} (nothing fires on the guarded "
                  "line)")

    n = len(result.findings) + len(result.bad_suppressions) + \
        len(result.errors)
    nw = len(result.warnings)
    code = result.exit_code(strict=args.strict,
                            warn_budget=args.warn_budget)
    if n or nw or (args.strict and result.unused_suppressions):
        extra = (f", {len(result.unused_suppressions)} unused "
                 "suppression(s)" if args.strict
                 and result.unused_suppressions else "")
        warn = ""
        if nw:
            budget = ("no budget" if args.warn_budget is None
                      else f"budget {args.warn_budget}")
            warn = f", {nw} warning(s) ({budget})"
        print(f"tpslint: {n} finding(s){warn}{extra}", file=sys.stderr)
    elif result.suppressed:
        print(f"tpslint: clean ({len(result.suppressed)} justified "
              "suppression(s))", file=sys.stderr)
    else:
        print("tpslint: clean", file=sys.stderr)
    return code


if __name__ == "__main__":
    sys.exit(main())
