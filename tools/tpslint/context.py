"""Traced-context discovery and traced-value taint analysis.

A *traced context* is a function body that JAX executes with tracer values:
anything jit-compiled, a ``lax`` control-flow body (``while_loop`` / ``scan``
/ ``cond`` / ``fori_loop`` / ``switch`` / ``map``), a ``shard_map`` body, a
Pallas kernel, or a function nested inside one of those (it runs during the
enclosing trace).  Host-callback targets (``io_callback`` / ``pure_callback``
/ ``jax.debug.callback``) are the explicit exception — they run on the host
even though they are *called from* traced code.

Within each traced context we compute a conservative set of *tainted* names:
the context's parameters (minus jit static args) plus anything assigned from
them, minus expressions that are static under tracing (``.shape`` / ``.dtype``
/ ``.ndim`` / ``len()`` — those concretize at trace time, not run time).
Rules use the taint set to tell ``float(rnorm)`` (a host sync on a traced
value) from ``float(rtol)`` (a host-side config scalar captured by closure).

Everything is per-module and purely syntactic: no imports are executed, so
the linter runs on files that need a TPU backend to even import.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field


# Callables whose function-valued argument(s) are traced.  Maps the terminal
# name (last attribute segment) to the positional indices of the traced
# function arguments.  Ambiguous terminals (AMBIGUOUS below) additionally
# require a ``lax``/``jax`` qualifier so that builtin ``map(f, xs)`` or an
# unrelated ``obj.cond(...)`` does not match.
TRACING_CALLERS = {
    "jit": (0,),
    "pjit": (0,),
    "shard_map": (0,),
    "pmap": (0,),
    "vmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "scan": (0,),
    "cond": (1, 2),
    "switch": (1,),
    "map": (0,),
    "associative_scan": (0,),
    "pallas_call": (0,),
}

#: Terminal names that only count when qualified by a jax/lax module alias.
AMBIGUOUS = {"map", "cond", "scan", "switch", "grad", "checkpoint"}

#: Decorator terminals that make the decorated function a traced context.
TRACING_DECORATORS = {"jit", "pjit", "shard_map", "pmap", "vmap", "grad",
                      "value_and_grad", "checkpoint", "remat"}

#: Callables whose function argument runs ON THE HOST (never traced).
HOST_CALLBACK_CALLERS = {"io_callback", "pure_callback", "callback",
                         "debug_callback"}

#: Attribute accesses that are static under tracing — reading them off a
#: tracer yields a concrete Python value at trace time, not a device value.
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                "itemsize", "weak_type"}

#: Calls that concretize to static values at trace time.
STATIC_CALLS = {"len", "isinstance", "type"}


def terminal_name(func: ast.expr):
    """``jax.lax.psum`` -> ``psum``; ``psum`` -> ``psum``; else None."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def qualifier_chain(func: ast.expr):
    """Dotted prefix of an Attribute as a list: ``jax.lax.psum`` ->
    ``["jax", "lax"]``; bare names and non-name bases -> ``[]``."""
    chain = []
    cur = func.value if isinstance(func, ast.Attribute) else None
    while isinstance(cur, ast.Attribute):
        chain.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        chain.append(cur.id)
    chain.reverse()
    return chain


@dataclass
class ModuleInfo:
    """Import aliases gathered from the module header."""

    numpy_aliases: set = field(default_factory=set)   # np, numpy
    jnp_aliases: set = field(default_factory=set)     # jnp, jax.numpy
    jax_aliases: set = field(default_factory=set)     # jax
    lax_aliases: set = field(default_factory=set)     # lax
    # names from-imported out of jax.* modules: name -> source module
    jax_from_imports: dict = field(default_factory=dict)

    def collect(self, tree: ast.AST):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if a.name in ("numpy",):
                        self.numpy_aliases.add(name)
                    elif a.name in ("jax.numpy",):
                        if a.asname:
                            self.jnp_aliases.add(a.asname)
                        else:
                            # ``import jax.numpy`` binds the name "jax";
                            # jax.numpy.* is matched via the dotted chain
                            self.jax_aliases.add("jax")
                    elif a.name == "jax":
                        self.jax_aliases.add(name)
                    elif a.name in ("jax.lax",):
                        if a.asname:
                            self.lax_aliases.add(a.asname)
                        else:
                            self.jax_aliases.add("jax")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    name = a.asname or a.name
                    if mod == "jax" and a.name == "numpy":
                        self.jnp_aliases.add(name)
                    elif mod == "jax" and a.name == "lax":
                        self.lax_aliases.add(name)
                    elif mod.startswith("jax"):
                        self.jax_from_imports[name] = mod
                    elif mod == "numpy":
                        # from numpy import float64 — track via from-imports
                        self.jax_from_imports.setdefault(name, mod)
        return self

    def is_lax_qualified(self, func: ast.expr) -> bool:
        """True when ``func`` plausibly refers to a jax/lax callable: a
        ``lax.x`` / ``jax.lax.x`` / ``jax.x`` attribute, or a bare name that
        was from-imported out of a jax module."""
        if isinstance(func, ast.Attribute):
            chain = qualifier_chain(func)
            if not chain:
                return False
            return (chain[-1] in self.lax_aliases or chain[-1] == "lax"
                    or chain[0] in self.jax_aliases)
        if isinstance(func, ast.Name):
            return func.id in self.jax_from_imports
        return False

    def is_numpy_attr(self, node: ast.expr) -> bool:
        """True for any attribute rooted at a numpy alias — ``np.asarray``
        but also submodule spellings like ``np.linalg.norm``."""
        if not isinstance(node, ast.Attribute):
            return False
        chain = qualifier_chain(node)
        return bool(chain) and chain[0] in self.numpy_aliases

    def is_jnp_attr(self, node: ast.expr) -> bool:
        """True for attributes rooted at a jax.numpy alias (``jnp.zeros``,
        ``jnp.linalg.norm``) or spelled ``jax.numpy.*`` directly."""
        if not isinstance(node, ast.Attribute):
            return False
        chain = qualifier_chain(node)
        if not chain:
            return False
        if chain[0] in self.jnp_aliases:
            return True
        return (len(chain) >= 2 and chain[0] in self.jax_aliases
                and chain[1] == "numpy")


@dataclass
class TracedContext:
    """One traced function body plus its tainted-name set."""

    node: ast.AST                 # FunctionDef / AsyncFunctionDef / Lambda
    reason: str                   # how it became traced ("jit", "while_loop" …)
    tainted: set = field(default_factory=set)

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")


FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class ModuleAnalysis:
    """Parsed module plus everything the rules need: import aliases, parent
    links, traced contexts with taint sets, and per-context node iteration."""

    def __init__(self, tree: ast.Module, source: str, path: str = "<string>"):
        self.tree = tree
        self.source = source
        self.path = path
        #: the project-wide ProgramIndex (tools/tpslint/program.py), set
        #: by the engine's phase-1 indexing pass before any rule runs —
        #: every rule can follow calls across the analyzed file set
        self.program = None
        self.info = ModuleInfo().collect(tree)
        self.parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self._host_marked = set()     # function nodes passed to host callbacks
        self._trace_reasons = {}      # function node -> reason string
        self._call_statics = {}       # function node -> static names from
                                      # call-form jax.jit(fn, static_arg...)
        self._find_marked_functions()
        self.contexts = self._build_contexts()

    # ------------------------------------------------------------------ marks
    def _resolve_func_arg(self, call: ast.Call, index: int):
        """The function node an argument refers to: a Lambda literal, or a
        Name resolved to a def in an enclosing scope of the call site."""
        if index >= len(call.args):
            return None
        arg = call.args[index]
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Call):
            # jax.jit(comm.shard_map(local_fn, ...)) — the inner call is
            # itself a tracing caller; it gets handled on its own visit.
            return None
        if isinstance(arg, ast.Name):
            return self._resolve_name_to_def(arg)
        if isinstance(arg, (ast.List, ast.Tuple)):
            # lax.switch branch lists — handled by caller
            return None
        return None

    def _resolve_name_to_def(self, name: ast.Name):
        """Nearest def of ``name`` walking up the scope chain.

        Follows Python scoping: class bodies are NOT enclosing scopes for
        names used inside methods, and a function parameter shadows any
        outer def of the same name (in which case the reference is not
        statically resolvable — return None rather than mis-binding)."""
        scope = self.parents.get(name)
        crossed_function = False
        while scope is not None:
            if isinstance(scope, FUNCTION_NODES):
                args = scope.args
                params = {a.arg for a in (args.posonlyargs + args.args
                                          + args.kwonlyargs)}
                if args.vararg:
                    params.add(args.vararg.arg)
                if args.kwarg:
                    params.add(args.kwarg.arg)
                if name.id in params:
                    return None          # bound to a parameter, not a def
                body = scope.body if isinstance(scope.body, list) else []
                for stmt in body:
                    if (isinstance(stmt, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                            and stmt.name == name.id):
                        return stmt
                crossed_function = True
            elif isinstance(scope, ast.ClassDef):
                if not crossed_function:   # reference directly in class body
                    for stmt in scope.body:
                        if (isinstance(stmt, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))
                                and stmt.name == name.id):
                            return stmt
            elif isinstance(scope, ast.Module):
                for stmt in scope.body:
                    if (isinstance(stmt, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                            and stmt.name == name.id):
                        return stmt
            scope = self.parents.get(scope)
        return None

    def _mark(self, fn_node, reason: str):
        if fn_node is not None and isinstance(fn_node, FUNCTION_NODES):
            self._trace_reasons.setdefault(fn_node, reason)

    def _find_marked_functions(self):
        """Single pass marking functions traced (or host) by decorator and
        by being passed to tracing/host-callback callers."""
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    name = self._decorator_terminal(dec)
                    if name in TRACING_DECORATORS:
                        if name in AMBIGUOUS and not self._dec_qualified(dec):
                            continue
                        self._mark(node, name)
            elif isinstance(node, ast.Call):
                name = terminal_name(node.func)
                if name in HOST_CALLBACK_CALLERS:
                    fn = self._resolve_func_arg(node, 0)
                    if fn is not None:
                        self._host_marked.add(fn)
                    elif node.args and isinstance(node.args[0], ast.Lambda):
                        self._host_marked.add(node.args[0])
                    continue
                if name not in TRACING_CALLERS:
                    continue
                if name in AMBIGUOUS and not self.info.is_lax_qualified(
                        node.func):
                    continue
                for idx in TRACING_CALLERS[name]:
                    fn = self._resolve_func_arg(node, idx)
                    self._mark(fn, name)
                    if (fn is not None and name in ("jit", "pjit")
                            and isinstance(fn, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))):
                        statics = self._statics_from_keywords(node.keywords,
                                                              fn)
                        if statics:
                            self._call_statics.setdefault(
                                fn, set()).update(statics)
                    if (name == "switch" and idx < len(node.args)
                            and isinstance(node.args[idx],
                                           (ast.List, ast.Tuple))):
                        for elt in node.args[idx].elts:
                            if isinstance(elt, ast.Lambda):
                                self._mark(elt, name)
                            elif isinstance(elt, ast.Name):
                                self._mark(self._resolve_name_to_def(elt),
                                           name)

    def _decorator_terminal(self, dec: ast.expr):
        """Terminal name of a decorator, looking through ``partial(...)``."""
        if isinstance(dec, ast.Call):
            inner = terminal_name(dec.func)
            if inner == "partial" and dec.args:
                return terminal_name(dec.args[0])
            return inner
        return terminal_name(dec)

    def _dec_qualified(self, dec: ast.expr) -> bool:
        if isinstance(dec, ast.Call):
            if terminal_name(dec.func) == "partial" and dec.args:
                return self.info.is_lax_qualified(dec.args[0])
            return self.info.is_lax_qualified(dec.func)
        return self.info.is_lax_qualified(dec)

    # -------------------------------------------------------------- contexts
    def _build_contexts(self):
        """Traced contexts in source order, taint sets computed with
        enclosing-context taint inherited by closures."""
        contexts = []
        index = {}

        def visit(node, enclosing_tainted, enclosing_traced):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, FUNCTION_NODES):
                    if child in self._host_marked:
                        # host-callback target: nothing inside it is traced
                        visit(child, set(), False)
                        continue
                    traced = (child in self._trace_reasons
                              or enclosing_traced)
                    if traced:
                        reason = self._trace_reasons.get(child, "enclosing")
                        tainted = self._seed_taint(child)
                        tainted |= self._free_tainted(child,
                                                      enclosing_tainted)
                        self._propagate(child, tainted)
                        ctx = TracedContext(child, reason, tainted)
                        contexts.append(ctx)
                        index[child] = ctx
                        visit(child, tainted, True)
                    else:
                        visit(child, set(), False)
                else:
                    visit(child, enclosing_tainted, enclosing_traced)

        visit(self.tree, set(), False)
        self._ctx_index = index
        return contexts

    def _seed_taint(self, fn) -> set:
        """Parameters of a traced function are tracers — minus jit static
        args declared in the decorator."""
        args = fn.args
        names = {a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)}
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
        return names - self._static_argnames(fn)

    @staticmethod
    def _statics_from_keywords(keywords, fn) -> set:
        """Parameter names made static by static_argnames/static_argnums
        keywords (of a jit decorator or a call-form ``jax.jit(fn, ...)``)."""
        static = set()
        pos_params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for kw in keywords:
            if kw.arg == "static_argnames":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(
                            c.value, str):
                        static.add(c.value)
            elif kw.arg == "static_argnums":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(
                            c.value, int):
                        if 0 <= c.value < len(pos_params):
                            static.add(pos_params[c.value])
        return static

    def _static_argnames(self, fn) -> set:
        """static_argnames/static_argnums declared on a jit decorator or
        recorded from a call-form ``jax.jit(fn, static_argnums=...)``."""
        static = set(self._call_statics.get(fn, ()))
        for dec in getattr(fn, "decorator_list", []):
            if not isinstance(dec, ast.Call):
                continue
            if terminal_name(dec.func) == "partial" and dec.args:
                if terminal_name(dec.args[0]) not in ("jit", "pjit"):
                    continue
            elif terminal_name(dec.func) not in ("jit", "pjit"):
                continue
            # partial(jax.jit, ...) shifts nothing: the decorated fn's own
            # positional order applies
            static |= self._statics_from_keywords(dec.keywords, fn)
        return static

    def _free_tainted(self, fn, enclosing_tainted) -> set:
        """Enclosing tainted names the closure actually references."""
        if not enclosing_tainted:
            return set()
        used = {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}
        return enclosing_tainted & used

    def _propagate(self, fn, tainted: set):
        """Forward passes over the context's own statements in SOURCE order
        (iter_own_nodes yields DFS-stack order), adding assignment targets
        whose RHS is tainted, iterated to a fixpoint so arbitrarily long
        assignment chains (`b = x; c = b; d = c; float(d)`) taint fully."""
        stmts = sorted(self.iter_own_nodes(fn),
                       key=lambda n: (getattr(n, "lineno", 0),
                                      getattr(n, "col_offset", 0)))
        for _ in range(len(stmts) + 1):
            before = len(tainted)
            for node in stmts:
                if isinstance(node, ast.Assign):
                    if self.expr_tainted(node.value, tainted):
                        for t in node.targets:
                            self._add_targets(t, tainted)
                elif isinstance(node, ast.AnnAssign) and node.value:
                    if self.expr_tainted(node.value, tainted):
                        self._add_targets(node.target, tainted)
                elif isinstance(node, ast.AugAssign):
                    if self.expr_tainted(node.value, tainted):
                        self._add_targets(node.target, tainted)
                elif isinstance(node, ast.NamedExpr):
                    if self.expr_tainted(node.value, tainted):
                        self._add_targets(node.target, tainted)
                elif isinstance(node, ast.For):
                    if self.expr_tainted(node.iter, tainted):
                        self._add_targets(node.target, tainted)
            if len(tainted) == before:
                break

    @staticmethod
    def _add_targets(target, tainted: set):
        """Taint the names an assignment target binds.  For subscript /
        attribute targets only the base is tainted — ``tau[i][j] = x`` says
        nothing about the index variables ``i``/``j``."""
        if isinstance(target, ast.Name):
            tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                ModuleAnalysis._add_targets(elt, tainted)
        elif isinstance(target, ast.Starred):
            ModuleAnalysis._add_targets(target.value, tainted)
        elif isinstance(target, (ast.Subscript, ast.Attribute)):
            ModuleAnalysis._add_targets(target.value, tainted)

    # ------------------------------------------------------------- utilities
    def expr_tainted(self, expr: ast.expr, tainted: set) -> bool:
        """Does ``expr`` carry a traced value?  Static-under-tracing
        subtrees (``x.shape[0]``, ``len(x)``, ``x.dtype``) do not count."""
        if expr is None or not tainted:
            return False
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Attribute):
                if node.attr in STATIC_ATTRS:
                    continue                      # static subtree: skip whole
                stack.append(node.value)
                continue
            if isinstance(node, ast.Call):
                tname = terminal_name(node.func)
                if tname in STATIC_CALLS:
                    continue
                stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Name):
                if node.id in tainted:
                    return True
                continue
            if isinstance(node, ast.Lambda):
                continue                          # deferred body
            stack.extend(ast.iter_child_nodes(node))
        return False

    def iter_own_nodes(self, fn):
        """All nodes of a function body EXCLUDING nested function bodies —
        nested defs are their own (traced or host) contexts."""
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        stack = list(body)
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, FUNCTION_NODES):
                    # still yield the def node itself (rules may inspect
                    # decorators) but do not descend into its body
                    yield child
                    continue
                stack.append(child)

    def context_for(self, fn_node):
        return self._ctx_index.get(fn_node)
