"""tpslint — JAX/TPU-aware static analysis for this repo's solver stack.

The performance story of the TPU sparse-solve reproduction rests on
invariants the Python type system cannot see:

* solves compile to ONE XLA program with O(1) host syncs — a stray
  ``float(traced)`` inside a ``while_loop`` body silently re-introduces a
  per-iteration device->host round trip (README "One XLA program per
  solve");
* collectives must name the mesh axis ``DeviceComm`` actually created
  (``parallel/mesh.py``), never a hard-coded string;
* dtype discipline decides whether the MXU fast path or the emulated-f64
  path runs (``TPU_SOLVE_NO_X64``).

tpslint walks the AST (no imports, no execution — safe on files that need
a TPU to even import), detects *traced contexts* (jit-compiled functions,
``lax`` control-flow bodies, ``shard_map`` bodies, Pallas kernels) plus a
per-context traced-value taint set, and checks the rule registry in
:mod:`tools.tpslint.rules` against them.

Run ``tpslint --list-rules`` for the rule table, or see README
"Static analysis".
"""

from .engine import AnalysisResult, analyze_paths, analyze_source
from .rules import all_rules
from .findings import Finding, Suppression

__version__ = "0.1.0"

__all__ = [
    "AnalysisResult",
    "Finding",
    "Suppression",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "__version__",
]
