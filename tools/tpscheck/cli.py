"""``tpscheck`` console entry point.

Usage::

    tpscheck                         # check every registered contract
    tpscheck --strict --sarif contracts.sarif
    tpscheck --select megasolve/cg,ksp/pipecg/ell
    tpscheck --kinds ksp_many,megasolve
    tpscheck --changed-files $(git diff --name-only base... -- '*.py')
    tpscheck --index-cache .tpslint-cache/contracts.json
    tpscheck --update-baseline       # snapshot observed metrics
    tpscheck --list-contracts

Lowers each registered program class (``mpi_petsc4py_example_tpu/
contracts.py``) over 8 forced host CPU devices, measures the
communication schedule from the StableHLO, and diffs it against the
declaration.  ``--changed-files`` re-checks only contracts whose
declared dependency modules (or the registry/parser/checker themselves)
changed; ``--index-cache`` persists measured metrics keyed on a
dependency content hash — the tpslint index-cache discipline applied to
lowerings, so an unchanged contract costs a hash, not a trace.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

#: sources that invalidate EVERY contract when they change — the
#: registry, the HLO parser, and the checker itself
GLOBAL_DEPS = (
    "mpi_petsc4py_example_tpu/contracts.py",
    "mpi_petsc4py_example_tpu/utils/hlo.py",
    "tools/tpscheck/checker.py",
)


def _bootstrap_env():
    """Force the 8-device host platform BEFORE jax initializes — the
    grid every contract's budgets are declared against."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flag = "--xla_force_host_platform_device_count=8"
    xf = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xf:
        os.environ["XLA_FLAGS"] = f"{xf} {flag}".strip()


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpscheck",
        description=("program-contract verifier: lowers every "
                     "registered solver program class to StableHLO and "
                     "diffs its communication schedule against the "
                     "declarative contract registry"))
    p.add_argument("--strict", action="store_true",
                   help="fail on baseline drift too (warn-tier "
                        "findings behave as under --warn-budget 0)")
    p.add_argument("--warn-budget", type=int, default=None, metavar="N",
                   help="fail when warn-tier findings (baseline drift) "
                        "exceed N")
    p.add_argument("--select", default=None, metavar="NAME,NAME",
                   help="comma-separated contract names to check")
    p.add_argument("--kinds", default=None, metavar="KIND,KIND",
                   help="comma-separated program kinds to check")
    p.add_argument("--changed-files", nargs="+", default=None,
                   metavar="PATH",
                   help="check only contracts whose declared dependency "
                        "modules intersect these files (registry/parser"
                        "/checker changes re-check everything)")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="also write findings as a SARIF 2.1.0 log")
    p.add_argument("--index-cache", default=None, metavar="PATH",
                   help="JSON cache of measured metrics keyed on a "
                        "dependency content hash; an unchanged "
                        "contract skips its lowering")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="drift baseline to compare against (default: "
                        "the committed tools/tpscheck/baseline.json)")
    p.add_argument("--update-baseline", action="store_true",
                   help="write the observed metrics of the checked "
                        "contracts into the baseline and exit by the "
                        "contract findings alone")
    p.add_argument("--list-contracts", action="store_true",
                   help="print the contract table and exit")
    return p


def _repo_rel(path: str, root: str) -> str:
    """Normalize a ``--changed-files`` path to repo-root-relative form.

    Relative paths are taken as repo-ROOT-relative — the form
    ``git diff --name-only`` emits — not CWD-relative, so invoking
    tpscheck from a subdirectory cannot silently deselect every
    contract and false-pass the gate.  Absolute paths are relativized
    against the root.
    """
    if not os.path.isabs(path):
        path = os.path.join(root, path)
    rel = os.path.relpath(path, root)
    return rel.replace(os.sep, "/")


def _dep_files(contract) -> tuple:
    return tuple(contract.deps) + GLOBAL_DEPS


def _dep_hash(contract, root: str) -> str:
    h = hashlib.sha256()
    for rel in sorted(set(_dep_files(contract))):
        h.update(rel.encode())
        try:
            with open(os.path.join(root, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(b"<missing>")
    h.update(".".join(map(str, sys.version_info[:2])).encode())
    return h.hexdigest()


def _load_cache(path):
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def _save_cache(path, cache):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(cache, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    _bootstrap_env()

    from tools.tpscheck import checker
    root = str(checker.REPO_ROOT)

    from mpi_petsc4py_example_tpu import contracts as registry

    if args.list_contracts:
        for c in registry.contracts():
            print(f"{c.name}  [{c.kind}]")
            print(f"        {c.description}")
        return 0

    names = kinds = None
    if args.select:
        names = [s.strip() for s in args.select.split(",") if s.strip()]
    if args.kinds:
        kinds = [s.strip() for s in args.kinds.split(",") if s.strip()]
        unknown = set(kinds) - set(registry.PROGRAM_KINDS)
        if unknown:
            print(f"tpscheck: error: unknown kind(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
    try:
        selected = registry.get_contracts(names=names, kinds=kinds)
    except KeyError as exc:
        print(f"tpscheck: error: {exc.args[0]}", file=sys.stderr)
        return 2

    # ---- changed-files scope: dependency-driven selection ----
    if args.changed_files is not None:
        changed = {_repo_rel(p, root) for p in args.changed_files}
        if changed & set(GLOBAL_DEPS) or any(
                c.startswith("tools/tpscheck/") for c in changed):
            pass        # registry/parser/checker changed: keep them all
        else:
            selected = tuple(c for c in selected
                             if set(c.deps) & changed)
        if not selected:
            print("tpscheck: clean (no contract depends on the changed "
                  "files)", file=sys.stderr)
            if args.sarif:
                from tools.tpslint.engine import AnalysisResult
                from tools.tpslint.sarif import write_sarif
                write_sarif(args.sarif, AnalysisResult(), checker.RULES,
                            base_dir=root)
            return 0

    baseline = {}
    if not args.update_baseline:
        baseline = checker.load_baseline(
            args.baseline or checker.BASELINE_PATH)

    cache = _load_cache(args.index_cache) if args.index_cache else {}

    # ---- check: cached measurements skip their lowering ----
    from tools.tpslint.engine import AnalysisResult
    result = AnalysisResult()
    result.measured = {}
    comm = None
    hits = 0
    for contract in selected:
        key = _dep_hash(contract, root)
        entry = cache.get(contract.name)
        if entry is not None and entry.get("key") == key:
            m = entry["measured"]
            findings = list(checker._diff(contract, m))
            if baseline:
                findings.extend(
                    checker._baseline_drift(contract, m, baseline))
            hits += 1
        else:
            if comm is None:
                import mpi_petsc4py_example_tpu as tps
                comm = tps.DeviceComm()
            findings, m = checker.check_contract(contract, comm,
                                                 baseline=baseline)
        if m is not None:
            result.measured[contract.name] = m
            result.files_linted += 1
            cache[contract.name] = {"key": key, "measured": m}
        for f in findings:
            if f.rule == checker.LOWER_ERROR:
                result.errors.append(f)
            elif f.severity == "warn":
                result.warnings.append(f)
            else:
                result.findings.append(f)

    if args.index_cache:
        _save_cache(args.index_cache, cache)

    if args.update_baseline:
        path = args.baseline or checker.BASELINE_PATH
        merged = checker.load_baseline(path)
        merged.update(result.measured)
        _save_cache(str(path), merged)
        print(f"tpscheck: baseline updated "
              f"({len(result.measured)} contract(s))", file=sys.stderr)

    if args.sarif:
        from tools.tpslint.sarif import write_sarif
        write_sarif(args.sarif, result, checker.RULES, base_dir=root)

    for f in result.errors + result.findings + result.warnings:
        print(f.format())

    n = len(result.findings) + len(result.errors)
    nw = len(result.warnings)
    warn_budget = args.warn_budget
    if args.strict and warn_budget is None:
        warn_budget = 0
    code = result.exit_code(strict=args.strict, warn_budget=warn_budget)
    cached = f", {hits} cached" if hits else ""
    if n or nw:
        print(f"tpscheck: {n} finding(s), {nw} drift warning(s) over "
              f"{result.files_linted} contract(s){cached}",
              file=sys.stderr)
    else:
        print(f"tpscheck: clean ({result.files_linted} contract(s)"
              f"{cached})", file=sys.stderr)
    return code


if __name__ == "__main__":
    sys.exit(main())
