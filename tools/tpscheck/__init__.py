"""tpscheck — the lowered-StableHLO program-contract verifier.

The second static-analysis backend (round 16): where ``tools/tpslint``
reads Python ASTs, tpscheck lowers every program class registered in
``mpi_petsc4py_example_tpu/contracts.py`` over a small host device grid,
parses the StableHLO with ``mpi_petsc4py_example_tpu/utils/hlo.py``, and
diffs the observed communication schedule against the declared contract
— reduce-site chains, collective byte budgets, gather-op counts,
reduce-channel dtypes, donation markers. Findings ride the tpslint
``Finding``/SARIF pipeline, so CI annotations and ``--strict`` gating
work identically across both backends.
"""
