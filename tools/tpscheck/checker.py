"""tpscheck core: lower each registered contract, measure, diff.

Check rules (the TPC numbering space is disjoint from tpslint's TPS so
one SARIF run can carry both):

* TPC001 — reduce-site chain: per-depth own ``all_reduce`` counts along
  the largest while chain differ from the declared schedule;
* TPC002 — gather volume: an ``all_gather`` site's element/byte volume
  is off budget (replication or full-width regressions);
* TPC003 — gather site count: the ``all_gather`` op count drifted (the
  k-independence and per-iteration-site pins);
* TPC004 — channel shape: a gather appeared in a gather-free (banded)
  program, or the ppermute halo sites/bytes are off;
* TPC005 — reduce dtype: an ``all_reduce`` result dtype left the
  declared reduce channel;
* TPC006 — donation: the donated-argument/alias markers are missing;
* TPC007 — total reduce sites: the whole-program ``all_reduce`` count
  drifted (the absolute form of guarded-vs-plain / rr-on-off pins);
* TPC008 — baseline drift: an UNPINNED measured metric changed vs the
  committed ``baseline.json`` (run ``tpscheck --update-baseline`` after
  auditing the change);
* TPC-LOWER — the contract's program failed to lower at all.

Findings anchor at the contract's ``name="..."`` line in
``contracts.py`` — the file a reviewer edits to change the declaration.
"""

from __future__ import annotations

import functools
import json
import re
from dataclasses import dataclass
from pathlib import Path

from tools.tpslint.engine import AnalysisResult
from tools.tpslint.findings import Finding

REPO_ROOT = Path(__file__).resolve().parents[2]
CONTRACTS_REL = "mpi_petsc4py_example_tpu/contracts.py"
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"
LOWER_ERROR = "TPC-LOWER"


@dataclass(frozen=True)
class CheckRule:
    """SARIF-compatible rule descriptor (same attribute shape the
    tpslint registry exposes to ``tools.tpslint.sarif``)."""

    id: str
    name: str
    description: str
    severity: str = "error"


_RULES = (
    CheckRule("TPC001", "reduce-site-chain",
              "per-depth own all_reduce counts of the lowered program "
              "must match the contract's declared schedule"),
    CheckRule("TPC002", "gather-volume",
              "every all_gather site's element/byte volume must match "
              "the contract's budget (a larger gather is replication; "
              "same elems at more bytes is a full-width upcast)"),
    CheckRule("TPC003", "gather-site-count",
              "the all_gather op count must match the declaration — "
              "batched programs must not grow sites with the RHS block "
              "width"),
    CheckRule("TPC004", "channel-shape",
              "gather-free (banded/stencil) programs must stay "
              "gather-free, and the ppermute halo site count / byte "
              "total must match the declaration"),
    CheckRule("TPC005", "reduce-dtype",
              "all_reduce result dtypes must stay inside the declared "
              "reduce channel (a silently narrowed exit-gate psum "
              "changes convergence semantics)"),
    CheckRule("TPC006", "donation",
              "donated programs must carry the declared buffer-donor / "
              "aliasing markers (a pruned donation doubles solve "
              "residency)"),
    CheckRule("TPC007", "total-reduce-sites",
              "the whole-program all_reduce count must match the "
              "declaration (init + loop + epilogue)"),
    CheckRule("TPC008", "baseline-drift",
              "a measured metric not pinned by the contract changed "
              "against the committed baseline — audit, then "
              "`tpscheck --update-baseline`", "warn"),
)

#: rule registry in the shape ``tools.tpslint.sarif.to_sarif`` expects
RULES = {r.id: r for r in _RULES}


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def measure(stablehlo_text: str) -> dict:
    """The full observed metric set of one lowered program — the shape
    both the contract diff and the committed baseline use."""
    from mpi_petsc4py_example_tpu.utils import hlo
    gathers = hlo.collective_sites(stablehlo_text, "all_gather")
    perms = hlo.collective_sites(stablehlo_text, "collective_permute")
    reduce_dtypes = hlo.reduce_site_dtypes(stablehlo_text)
    return {
        "reduce_site_chain": list(
            hlo.nested_loop_reduce_site_chain(stablehlo_text)),
        "total_reduce_sites": len(reduce_dtypes),
        "reduce_dtypes": sorted({e for t in reduce_dtypes for e in t}),
        "gather_sites": len(gathers),
        "gather_elems": sorted({s.elems for s in gathers}),
        "gather_bytes": sorted({s.bytes for s in gathers}),
        "ppermute_sites": len(perms),
        "ppermute_total_bytes": sum(s.bytes for s in perms),
        "donated_args": list(hlo.donated_args(stablehlo_text)),
        "aliased_outputs": len(
            hlo.input_output_aliases(stablehlo_text)),
    }


@functools.lru_cache(maxsize=1)
def _contract_lines() -> dict:
    """``contract name -> 1-based line`` of its ``name="..."`` literal
    in contracts.py, so findings anchor where the declaration lives."""
    out = {}
    try:
        src = (REPO_ROOT / CONTRACTS_REL).read_text(encoding="utf-8")
    except OSError:
        return out
    for i, line in enumerate(src.splitlines(), 1):
        m = re.search(r"name=\"([^\"]+)\"", line)
        if m and m.group(1) not in out:
            out[m.group(1)] = i
    return out


def _finding(rule_id: str, contract, message: str,
             severity: str | None = None) -> Finding:
    sev = severity or RULES.get(rule_id, CheckRule("", "", "")).severity
    return Finding(rule=rule_id,
                   message=f"[{contract.name}] {message}",
                   line=_contract_lines().get(contract.name, 1),
                   col=0, path=CONTRACTS_REL,
                   severity=sev or "error")


# ---------------------------------------------------------------------------
# the diff
# ---------------------------------------------------------------------------


def _diff(contract, m: dict):
    """Yield findings for every declared expectation the measured
    metrics ``m`` violate."""
    c = contract
    if (c.reduce_site_chain is not None
            and tuple(m["reduce_site_chain"]) != tuple(
                c.reduce_site_chain)):
        yield _finding(
            "TPC001", c,
            f"reduce-site chain {m['reduce_site_chain']} != declared "
            f"{list(c.reduce_site_chain)} — the per-iteration psum "
            "schedule changed")
    if (c.total_reduce_sites is not None
            and m["total_reduce_sites"] != c.total_reduce_sites):
        yield _finding(
            "TPC007", c,
            f"whole-program all_reduce count {m['total_reduce_sites']} "
            f"!= declared {c.total_reduce_sites}")
    if c.reduce_dtypes is not None:
        extra = set(m["reduce_dtypes"]) - set(c.reduce_dtypes)
        if extra:
            yield _finding(
                "TPC005", c,
                f"all_reduce result dtype(s) {sorted(extra)} outside "
                f"the declared reduce channel "
                f"{sorted(c.reduce_dtypes)}")
    # --- gather channel ---
    if c.forbid_gathers and m["gather_sites"]:
        yield _finding(
            "TPC004", c,
            f"{m['gather_sites']} all_gather site(s) in a declared "
            "gather-free program (the halo-exchange VecScatter must "
            "carry the whole traffic)")
    if c.gather_sites is not None and m["gather_sites"] != c.gather_sites:
        yield _finding(
            "TPC003", c,
            f"all_gather op count {m['gather_sites']} != declared "
            f"{c.gather_sites}")
    if (c.gather_sites_max is not None
            and m["gather_sites"] > c.gather_sites_max):
        yield _finding(
            "TPC003", c,
            f"all_gather op count {m['gather_sites']} exceeds the "
            f"declared maximum {c.gather_sites_max}")
    if c.gather_elems is not None:
        bad = [v for v in m["gather_elems"] if v != c.gather_elems]
        if bad or not m["gather_elems"]:
            # an exact-elems pin implies the gather must EXIST — the
            # old `assert vols and all(v == n_pad ...)` shape
            yield _finding(
                "TPC002", c,
                f"all_gather element volumes {m['gather_elems']} != "
                f"declared {c.gather_elems} per site")
    if c.gather_elems_max is not None:
        bad = [v for v in m["gather_elems"] if v > c.gather_elems_max]
        if bad:
            yield _finding(
                "TPC002", c,
                f"all_gather element volume(s) {bad} exceed the "
                f"declared maximum {c.gather_elems_max} (a gather "
                "larger than one padded vector is replication)")
    if c.gather_bytes is not None:
        bad = [v for v in m["gather_bytes"] if v != c.gather_bytes]
        if bad:
            yield _finding(
                "TPC002", c,
                f"all_gather byte volumes {m['gather_bytes']} != "
                f"declared {c.gather_bytes} per site — same elements "
                "at more bytes is the full-width-upcast regression")
    # --- halo channel ---
    if (c.ppermute_sites is not None
            and m["ppermute_sites"] != c.ppermute_sites):
        yield _finding(
            "TPC004", c,
            f"collective_permute site count {m['ppermute_sites']} != "
            f"declared {c.ppermute_sites}")
    if (c.ppermute_sites_min is not None
            and m["ppermute_sites"] < c.ppermute_sites_min):
        yield _finding(
            "TPC004", c,
            f"collective_permute site count {m['ppermute_sites']} "
            f"below the declared minimum {c.ppermute_sites_min} — the "
            "halo exchange is missing")
    if (c.ppermute_total_bytes is not None
            and m["ppermute_total_bytes"] != c.ppermute_total_bytes):
        yield _finding(
            "TPC004", c,
            f"collective_permute total bytes "
            f"{m['ppermute_total_bytes']} != declared "
            f"{c.ppermute_total_bytes} (the storage-width halo "
            "budget)")
    # --- donation ---
    if (c.min_donated_args is not None
            and len(m["donated_args"]) < c.min_donated_args):
        yield _finding(
            "TPC006", c,
            f"{len(m['donated_args'])} buffer-donor argument(s) < "
            f"declared minimum {c.min_donated_args} — the donation "
            "was pruned or dropped")
    if (c.min_aliased_outputs is not None
            and m["aliased_outputs"] < c.min_aliased_outputs):
        yield _finding(
            "TPC006", c,
            f"{m['aliased_outputs']} committed input/output alias(es) "
            f"< declared minimum {c.min_aliased_outputs}")


def _baseline_drift(contract, m: dict, baseline: dict):
    entry = baseline.get(contract.name)
    if entry is None:
        return
    changed = sorted(k for k in entry if m.get(k) != entry[k])
    if changed:
        yield _finding(
            "TPC008", contract,
            f"unpinned metric(s) drifted vs the committed baseline: "
            + ", ".join(f"{k}: {entry[k]!r} -> {m.get(k)!r}"
                        for k in changed)
            + " — audit, then run `tpscheck --update-baseline`")


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def load_baseline(path=BASELINE_PATH) -> dict:
    """The committed observed-metrics snapshot; empty when absent (a
    fresh checkout before the first --update-baseline)."""
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def check_contract(contract, comm, baseline=None):
    """Lower one contract and return ``(findings, measured_or_None)``.

    A lowering failure is itself a finding (TPC-LOWER) — a contract
    whose program no longer builds must fail the gate, not vanish
    from it.
    """
    try:
        text = contract.build(comm)
    # tpslint: disable=TPS005 — ANY build failure must surface as a
    # TPC-LOWER gate finding (with the exception type in the message),
    # never escape the checker and take the whole run down with it
    except Exception as exc:   # noqa: BLE001
        msg = f"{type(exc).__name__}: {exc}"
        return [_finding(LOWER_ERROR, contract,
                         f"program failed to lower: {msg[:500]}",
                         severity="error")], None
    m = measure(text)
    findings = list(_diff(contract, m))
    if baseline:
        findings.extend(_baseline_drift(contract, m, baseline))
    return findings, m


def check_contracts(contracts, comm, baseline=None) -> AnalysisResult:
    """Check a contract collection into a tpslint-shaped
    :class:`AnalysisResult` (so ``--strict`` semantics, SARIF emission
    and exit codes are shared with the AST backend). The measured
    metrics of every successfully lowered contract land in
    ``result.measured`` for baseline writing."""
    result = AnalysisResult()
    result.measured = {}
    for contract in contracts:
        findings, m = check_contract(contract, comm, baseline=baseline)
        if m is not None:
            result.measured[contract.name] = m
            result.files_linted += 1
        for f in findings:
            if f.rule == LOWER_ERROR:
                result.errors.append(f)
            elif f.severity == "warn":
                result.warnings.append(f)
            else:
                result.findings.append(f)
    return result
