#!/usr/bin/env python
"""tpurun — the framework's ``mpirun``: run a driver under N virtual ranks.

Usage::

    python tools/tpurun.py -n 4 driver.py [driver args...]

Spawns N threads, each executing ``driver.py`` as ``__main__`` with a
thread-local MPI rank (compat/mpi4py). Point-to-point sends/recvs and
collectives rendezvous in-process; device work (assembly, KSP/EPS solves)
executes once on the rank-0 thread over the full device mesh. This is the
TPU analog of the reference's oversubscribed ``mpirun -n N python test.py``
testing idiom (SURVEY.md §4) — the way to exercise multi-rank driver logic
without a cluster or MPI.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import traceback


def main():
    ap = argparse.ArgumentParser(prog="tpurun", add_help=True)
    ap.add_argument("-n", "--np", type=int, default=1,
                    help="number of virtual ranks (threads)")
    ap.add_argument("script", help="driver script to run")
    ap.add_argument("args", nargs=argparse.REMAINDER,
                    help="arguments passed to the driver")
    opts = ap.parse_args()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    compat = os.path.join(repo, "compat")
    for p in (repo, compat):
        if p not in sys.path:
            sys.path.insert(0, p)
    from mpi_petsc4py_example_tpu.utils.phases import stamp
    stamp("tpurun_main")         # interpreter + site imports are behind us
    # like ``python script.py`` (and mpirun): the script's own directory leads
    # sys.path, so a driver's sibling modules (e.g. the reference repo's
    # petsc_funcs.py, /root/reference/test2.py:4) shadow the compat copies
    script_dir = os.path.dirname(os.path.abspath(opts.script))
    if script_dir in sys.path:
        sys.path.remove(script_dir)
    sys.path.insert(0, script_dir)

    sys.argv = [opts.script] + opts.args

    from mpi4py import MPI as _MPI  # the facade (compat/ is on sys.path)

    with open(opts.script) as f:
        code = compile(f.read(), opts.script, "exec")
    stamp("driver_exec")

    nprocs = opts.np
    errors: list = []

    if nprocs == 1:
        _MPI._set_context(None)
        g = {"__name__": "__main__", "__file__": opts.script,
             "__builtins__": __builtins__}
        exec(code, g)
        return 0

    ctx = _MPI.VirtualContext(nprocs)
    _MPI._set_context(ctx)

    def run_rank(rank: int):
        ctx.register(rank)
        g = {"__name__": "__main__", "__file__": opts.script,
             "__builtins__": __builtins__}
        try:
            exec(code, g)
        # tpslint: disable=TPS005 — rank thread runs an arbitrary user
        # script: even SystemExit/KeyboardInterrupt must be reported and
        # must release peers blocked on collectives
        except BaseException as e:  # noqa: BLE001
            errors.append((rank, e, traceback.format_exc()))
            # release peers blocked on collectives so the job aborts
            ctx.barrier.abort()

    threads = [threading.Thread(target=run_rank, args=(r,), name=f"rank{r}")
               for r in range(nprocs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    _MPI._set_context(None)

    if errors:
        for rank, _, tb in errors:
            print(f"--- rank {rank} failed ---\n{tb}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
