"""Distributed Hermitian eigensolve — the reference test2.py flow, TPU backend.

Driver-equivalent of reference ``test2.py``: rank-0 builds the symmetric
tridiagonal family, scatters CSR row blocks (typed ``[buf, MPI.INT]`` sends,
as test2.py:59-61 does), all ranks assemble through the L4 wrapper
(``petsc_funcs.createPETScMat``) and solve the HEP eigenproblem
(``petsc_funcs.solveSLEPcEigenvalues``); rank 0 prints the eigenvalues.

Run:  python tools/tpurun.py -n 4 examples/eigensolve.py [-eps_nev 4]
"""

import numpy as np

from mpi4py import MPI

import petsc_funcs as pet

from mpi_petsc4py_example_tpu.models import tridiag_family
from mpi_petsc4py_example_tpu.parallel.partition import (
    row_partition, slice_csr_block)
from mpi_petsc4py_example_tpu.utils.options import init as options_init

import sys

options_init(sys.argv)


def main():
    comm = MPI.COMM_WORLD
    rank = comm.Get_rank()
    nprocs = comm.Get_size()

    if rank == 0:
        CSR = tridiag_family(100)
        shape = CSR.shape
        count, displ = row_partition(shape[0], nprocs)

        for i in range(1, nprocs):
            rs, re = int(displ[i]), int(displ[i] + count[i])
            indptr, indices, data = slice_csr_block(
                CSR.indptr, CSR.indices, CSR.data, rs, re)
            lengths = {"indptr": len(indptr), "indices": len(indices),
                       "data": len(data)}
            comm.send(lengths, dest=i)
            comm.Send([indptr.astype(np.int32), MPI.INT], dest=i)
            comm.Send([indices.astype(np.int32), MPI.INT], dest=i)
            comm.Send([data, MPI.DOUBLE], dest=i)

        rs, re = int(displ[0]), int(displ[0] + count[0])
        indptr, indices, data = slice_csr_block(CSR.indptr, CSR.indices,
                                                CSR.data, rs, re)
    else:
        lengths = comm.recv(source=0)
        indptr = np.empty(lengths["indptr"], dtype=np.int32)
        indices = np.empty(lengths["indices"], dtype=np.int32)
        data = np.empty(lengths["data"], dtype=np.double)
        comm.Recv(indptr, source=0)
        comm.Recv(indices, source=0)
        comm.Recv(data, source=0)
        shape = None

    shape = comm.bcast(shape, root=0)

    A = pet.createPETScMat(comm, shape, (indptr, indices, data))
    E = pet.solveSLEPcEigenvalues(comm, A)

    nconv = E.getConverged()
    vr, wr = A.getVecs()
    vi, wi = A.getVecs()

    if rank == 0:
        for i in range(nconv):
            k = E.getEigenpair(i, vr, vi)
            print("Eigenvalue: ", k)


if __name__ == "__main__":
    main()
