#!/usr/bin/env python
"""Complex-scalar demo: 2D Helmholtz with an absorbing shift.

The canonical complex-build PETSc workload (reference analog: the
``solver_petsc_i`` flow of ``test.py:19-52`` run under a complex-scalar
PETSc build). Builds the shifted Helmholtz operator

    A = -Δh - (k² + iε) I

on an nx × nx grid (5-point Laplacian, Dirichlet), manufactures a complex
solution, solves with GMRES+Jacobi in complex128, and verifies against the
manufactured solution — printing ``True`` like the reference driver.

Usage::

    python examples/helmholtz.py [-n 48] [-ksp_type bcgs] [-ksp_rtol 1e-10]
"""

import os
import sys

# runnable standalone (python examples/helmholtz.py) as well as under
# tools/tpurun.py: make the repo root importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import scipy.sparse as sp

import mpi_petsc4py_example_tpu as tps

tps.init(sys.argv)


def helmholtz2d(nx: int, k2: float, eps: float):
    """-Δh - (k² + iε) I on an nx² grid (h=1 5-point stencil, Dirichlet)."""
    from mpi_petsc4py_example_tpu.models import poisson2d_csr
    lap = poisson2d_csr(nx).astype(np.complex128)
    return (lap - (k2 + 1j * eps) * sp.eye(nx * nx)).tocsr()


def main():
    opts = tps.global_options()
    nx = opts.get_int("n", 48)
    # keep the shifted operator definite enough for iterative solvers while
    # staying genuinely complex/indefinite-ish
    A = helmholtz2d(nx, k2=1.5, eps=0.5)
    n = nx * nx

    comm = tps.DeviceComm()
    M = tps.Mat.from_scipy(comm, A, dtype=np.complex128)

    rng = np.random.default_rng(42)
    x_true = rng.random(n) + 1j * rng.random(n)
    b = A @ x_true

    ksp = tps.KSP().create(comm)
    ksp.set_operators(M)
    ksp.set_type("gmres")
    ksp.get_pc().set_type("jacobi")
    ksp.set_tolerances(rtol=1e-10, max_it=5000)
    ksp.set_from_options()

    x, bv = M.get_vecs()
    bv.set_global(b)
    res = ksp.solve(bv, x)

    xs = x.to_numpy()
    ok = bool(np.allclose(xs, x_true, atol=1e-6))
    print(f"Helmholtz {nx}x{nx} (complex128): {ksp.get_type()} "
          f"{res.iterations} its, rel res "
          f"{np.linalg.norm(b - A @ xs) / np.linalg.norm(b):.2e}")
    print(ok)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
