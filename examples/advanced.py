"""Advanced-feature tour: matrix-free operators, PC composition, binary I/O.

Runs four short scenarios on the device mesh (any backend):

1. ShellMat — a never-assembled variable-coefficient operator solved with CG.
2. PCSHELL + PCCOMPOSITE — a user preconditioner and a multiplicative
   combination, via the options database (``-pc_type composite ...``).
3. PETSc binary interop — write the system to one ``.petsc`` file
   (Mat-then-Vec, the layout real PETSc tools consume), read it back, solve.
4. LOBPCG — smallest eigenpairs of the operator, verified by true residuals.

Usage: python examples/advanced.py [-ksp_type bcgs] [-pc_type gamg] ...
"""

import os
import sys
import tempfile

import numpy as np
import scipy.sparse as sp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp

import mpi_petsc4py_example_tpu as tps


def laplacian2d(nx):
    T = sp.diags([-np.ones(nx - 1), 2 * np.ones(nx), -np.ones(nx - 1)],
                 [-1, 0, 1])
    return (sp.kron(sp.eye(nx), T) + sp.kron(T, sp.eye(nx))).tocsr()


def main():
    tps.init(sys.argv)
    comm = tps.DeviceComm()
    nx = 24
    n = nx * nx
    A = laplacian2d(nx)
    w = 1.0 + np.arange(n) / n                     # variable coefficient
    Aw = (A + sp.diags(w)).tocsr()
    rng = np.random.default_rng(7)
    x_true = rng.random(n)
    b = Aw @ x_true

    # -- 1. matrix-free ShellMat --------------------------------------------
    Ad = jnp.asarray(A.toarray())
    wj = jnp.asarray(w)
    S = tps.ShellMat(comm, n, lambda v: Ad @ v + wj * v,
                     diagonal=A.diagonal() + w)
    ksp = tps.KSP().create(comm)
    ksp.set_operators(S)
    ksp.set_type("cg")
    ksp.get_pc().set_type("jacobi")
    ksp.set_tolerances(rtol=1e-10)
    ksp.set_from_options()
    x, bv = S.get_vecs()
    bv.set_global(b)
    res = ksp.solve(bv, x)
    print(f"1. shell operator: {res.reason_name} in {res.iterations} its, "
          f"max err {np.abs(x.to_numpy() - x_true).max():.2e}")

    # -- 2. user + composite preconditioning --------------------------------
    M = tps.Mat.from_scipy(comm, Aw)
    pc = tps.PC(comm)
    pc.set_type("composite")
    pc.set_composite_type("multiplicative")
    pc.set_composite_pcs("jacobi", "sor")
    ksp2 = tps.KSP().create(comm)
    ksp2.set_operators(M)
    ksp2.set_type("fgmres")
    ksp2.set_pc(pc)
    ksp2.set_tolerances(rtol=1e-10)
    x2, b2 = M.get_vecs()
    b2.set_global(b)
    res2 = ksp2.solve(b2, x2)
    print(f"2. composite(jacobi,sor): {res2.reason_name} in "
          f"{res2.iterations} its")

    # -- 3. PETSc binary round trip -----------------------------------------
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "system.petsc")
        with open(path, "wb") as f:
            tps.petsc_io.write_mat(f, Aw)
            tps.petsc_io.write_vec(f, b)
        with open(path, "rb") as f:
            A2 = tps.petsc_io.read_mat(f)
            b2h = tps.petsc_io.read_vec(f)
        M3 = tps.Mat.from_scipy(comm, A2)
        ksp3 = tps.KSP().create(comm)
        ksp3.set_operators(M3)
        ksp3.set_type("cg")
        ksp3.get_pc().set_type("jacobi")
        ksp3.set_tolerances(rtol=1e-10)
        x3, b3 = M3.get_vecs()
        b3.set_global(b2h)
        res3 = ksp3.solve(b3, x3)
        print(f"3. petsc-binary round trip: {res3.reason_name}, "
              f"max err {np.abs(x3.to_numpy() - x_true).max():.2e}")

    # -- 4. LOBPCG smallest eigenpairs --------------------------------------
    eps = tps.EPS().create(comm)
    eps.set_operators(M)
    eps.set_problem_type("hep")
    eps.set_type("lobpcg")
    eps.set_which_eigenpairs("smallest_real")
    eps.set_dimensions(nev=3)
    eps.set_tolerances(tol=1e-8, max_it=300)
    eps.solve()
    lams = [eps.get_eigenvalue(i).real for i in range(eps.get_converged())]
    errs = [eps.compute_error(i) for i in range(eps.get_converged())]
    print(f"4. lobpcg: {eps.get_converged()} pairs, "
          f"lambda_min={min(lams):.6f}, worst residual {max(errs):.1e}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
