"""Assemble the reference tridiagonal through Mat.setValues — facade demo.

The reference drivers hand the facade a prebuilt CSR triple
(``createAIJ(..., csr=...)``, test2.py:87); real petsc4py drivers just as
often assemble entry-by-entry with ``setValues`` + INSERT/ADD and
``assemblyBegin/End``. This driver builds reference test2.py's symmetric
tridiagonal family (``A[i,j] = i+j+1`` on the band) BOTH ways through the
facade — per-rank ``setValues`` of owned rows, then the ``csr=`` fast
path — and checks they agree entry for entry before solving the same
Hermitian eigenproblem ``test2.py`` solves.

Run:  python tools/tpurun.py -n 4 examples/assemble_setvalues.py
"""

import sys

import numpy as np

from mpi4py import MPI
from petsc4py import PETSc

from mpi_petsc4py_example_tpu.models import tridiag_family
from mpi_petsc4py_example_tpu.parallel.partition import (row_partition,
                                                         slice_csr_block)
from mpi_petsc4py_example_tpu.utils.options import init as options_init

options_init(sys.argv)

N = 100


def main():
    comm = MPI.COMM_WORLD
    rank = comm.Get_rank()
    nprocs = comm.Get_size()
    count, displ = row_partition(N, nprocs)
    rs, re = int(displ[rank]), int(displ[rank] + count[rank])

    # --- setValues assembly: each rank inserts its owned rows ------------
    A = PETSc.Mat().create(comm)
    A.setSizes((N, N))
    A.setType("aij")
    for i in range(rs, re):
        cols = [j for j in (i - 1, i, i + 1) if 0 <= j < N]
        vals = [float(i + j + 1) for j in cols]
        A.setValues([i], cols, vals, addv=PETSc.InsertMode.INSERT_VALUES)
    A.assemblyBegin()
    A.assemblyEnd()

    # --- the csr= fast path on the same matrix (per-rank local blocks,
    # the reference's rebased-CSR contract) ------------------------------
    CSR = tridiag_family(N)
    indptr, indices, data = slice_csr_block(CSR.indptr, CSR.indices,
                                            CSR.data, rs, re)
    B = PETSc.Mat().createAIJ(
        comm=comm, size=CSR.shape,
        csr=(indptr.astype(np.int32), indices.astype(np.int32), data))

    diff = abs(A.core.to_scipy() - B.core.to_scipy()).max()
    if rank == 0:
        print(f"setValues vs csr= max |diff|: {diff:.3e}")
    assert diff == 0.0, diff

    # --- the test2.py eigensolve on the setValues-assembled operator -----
    from slepc4py import SLEPc
    eps = SLEPc.EPS().create(comm)
    eps.setOperators(A)
    eps.setProblemType(SLEPc.EPS.ProblemType.HEP)
    eps.setFromOptions()
    eps.solve()
    if rank == 0 and eps.getConverged() >= 1:
        print(f"Eigenvalue: {eps.getEigenvalue(0).real:.9f}")


if __name__ == "__main__":
    main()
