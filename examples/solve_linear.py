"""Distributed direct solve of AX=B — the reference test.py flow, TPU backend.

Driver-equivalent of reference ``test.py`` (rank-0 builds a seeded random
sparse system, scatters CSR row blocks over the communicator, every rank
participates in a KSP ``preonly`` + PC ``lu`` (+'mumps' factor string) solve,
the solution is gathered and checked against the manufactured X). Written
fresh against the facade; the hand-rolled partition/slice idiom of the
reference (test.py:59-136) is replaced by the library partitioner.

Run single-rank:      python examples/solve_linear.py
Run 4 virtual ranks:  python tools/tpurun.py -n 4 examples/solve_linear.py
Override solver:      ... solve_linear.py -ksp_type cg -pc_type jacobi
"""

import sys

import numpy as np

import petsc4py

petsc4py.init(sys.argv)

from mpi4py import MPI
from petsc4py import PETSc

from mpi_petsc4py_example_tpu.models import random_system
from mpi_petsc4py_example_tpu.parallel.partition import (
    row_partition, slice_csr_block)

comm = MPI.COMM_WORLD
nprocs = comm.Get_size()
rank = comm.Get_rank()

FIELDS = ("indptr", "indices", "data", "rhs")

if rank == 0:
    A, X_actual, B_all = random_system(100, seed=42, density=0.1)
    shape = A.shape
    count, displ = row_partition(shape[0], nprocs)

    # scatter CSR row blocks + RHS blocks to the other ranks
    for i in range(1, nprocs):
        rs, re = int(displ[i]), int(displ[i] + count[i])
        indptr, indices, data = slice_csr_block(
            A.indptr, A.indices, A.data, rs, re)
        rhs = B_all[rs:re]
        parts = dict(zip(FIELDS, (indptr, indices, data, rhs)))
        comm.send({k: len(v) for k, v in parts.items()}, dest=i)
        comm.Send(indptr.astype(np.int32), dest=i)
        comm.Send(indices.astype(np.int32), dest=i)
        comm.Send(data, dest=i)
        comm.Send(rhs, dest=i)

    # rank 0's own block
    rs, re = int(displ[0]), int(displ[0] + count[0])
    indptr, indices, data = slice_csr_block(A.indptr, A.indices, A.data,
                                            rs, re)
    rhs = B_all[rs:re]
else:
    lengths = comm.recv(source=0)
    indptr = np.empty(lengths["indptr"], dtype=np.int32)
    indices = np.empty(lengths["indices"], dtype=np.int32)
    data = np.empty(lengths["data"], dtype=np.double)
    rhs = np.empty(lengths["rhs"], dtype=np.double)
    comm.Recv(indptr, source=0)
    comm.Recv(indices, source=0)
    comm.Recv(data, source=0)
    comm.Recv(rhs, source=0)
    shape = None

shape = comm.bcast(shape, root=0)

# ---- assemble + solve (all ranks, collective) ------------------------------
a = PETSc.Mat().createAIJ(comm=comm, size=shape,
                          csr=(indptr, indices, data))
a.setUp()
a.assemblyBegin()
a.assemblyEnd()
x, b = a.getVecs()
b.setArray(rhs)

ksp = PETSc.KSP().create(comm)
ksp.setType("preonly")
pc = ksp.getPC()
pc.setType("lu")
pc.setFactorSolverType("mumps")
ksp.setOperators(a)
ksp.setFromOptions()
ksp.setUp()
ksp.solve(b, x)

# ---- gather + verify --------------------------------------------------------
if rank == 0:
    X = np.empty(shape[0], dtype=np.double)
else:
    X = None
comm.Gatherv(x.array, X)

if rank == 0:
    ok = bool(np.allclose(X, X_actual))
    print(ok)
    if not ok:
        raise SystemExit("solution mismatch")
