// csrkit — native CSR toolkit for the TPU sparse framework.
//
// The reference's matrix assembly/distribution path is PETSc C code
// (MatCreateAIJ + MatAssembly, SURVEY.md N1) driven by hand-rolled Python
// slicing (test.py:83-117). Here the host-side data path — CSR validation,
// row-block slicing with indptr rebasing, CSR->ELL device-layout conversion,
// diagonal extraction — is native C++ behind a C ABI (ctypes), so assembling
// a 100M-row operator doesn't bottleneck in the Python interpreter. The
// Python layer (utils/native.py) compiles this on demand and falls back to
// vectorized numpy when no toolchain is available.
//
// All functions use int64 indptr, int32 column indices (sufficient to 100M
// DoF — matches the reference's int32 CSR indices, test.py:123-124) and
// float64 values; conversion to f32 happens on device_put.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <vector>

extern "C" {

// Validate a CSR triple: monotone indptr, in-range column indices.
// Returns 0 on success, a negative error code otherwise.
int csr_validate(const int64_t* indptr, int64_t nrows,
                 const int32_t* indices, int64_t nnz, int64_t ncols) {
    if (indptr[0] != 0) return -1;
    for (int64_t i = 0; i < nrows; ++i) {
        if (indptr[i + 1] < indptr[i]) return -2;
    }
    if (indptr[nrows] != nnz) return -3;
    for (int64_t k = 0; k < nnz; ++k) {
        if (indices[k] < 0 || indices[k] >= ncols) return -4;
    }
    return 0;
}

// Max nonzeros per row (the ELL width K).
int64_t csr_max_row_nnz(const int64_t* indptr, int64_t nrows) {
    int64_t k = 0;
    for (int64_t i = 0; i < nrows; ++i)
        k = std::max(k, indptr[i + 1] - indptr[i]);
    return k;
}

// CSR -> ELL: cols/vals are (nrows_pad, K) row-major, pre-zeroed by caller.
// Rows beyond nrows stay empty (padding rows of the device layout).
void csr_to_ell(const int64_t* indptr, const int32_t* indices,
                const double* data, int64_t nrows, int64_t K,
                int32_t* ell_cols, double* ell_vals) {
    for (int64_t i = 0; i < nrows; ++i) {
        const int64_t start = indptr[i], end = indptr[i + 1];
        int32_t* crow = ell_cols + i * K;
        double* vrow = ell_vals + i * K;
        for (int64_t p = start; p < end; ++p) {
            crow[p - start] = indices[p];
            vrow[p - start] = data[p];
        }
    }
}

// Slice rows [rstart, rend) into a rebased local block.
// local_indptr has rend-rstart+1 entries; local_indices/local_data hold
// indptr[rend]-indptr[rstart] entries (caller allocates from those bounds).
void csr_slice_rows(const int64_t* indptr, const int32_t* indices,
                    const double* data, int64_t rstart, int64_t rend,
                    int64_t* local_indptr, int32_t* local_indices,
                    double* local_data) {
    const int64_t p0 = indptr[rstart];
    for (int64_t i = rstart; i <= rend; ++i)
        local_indptr[i - rstart] = indptr[i] - p0;
    const int64_t nnz = indptr[rend] - p0;
    std::memcpy(local_indices, indices + p0, nnz * sizeof(int32_t));
    std::memcpy(local_data, data + p0, nnz * sizeof(double));
}

// Extract the matrix diagonal (missing diagonal entries stay 0).
void csr_diagonal(const int64_t* indptr, const int32_t* indices,
                  const double* data, int64_t nrows, double* diag) {
    for (int64_t i = 0; i < nrows; ++i) {
        diag[i] = 0.0;
        for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) {
            if (indices[p] == i) { diag[i] = data[p]; break; }
        }
    }
}

// Row L1 norms (for diagnostics / Jacobi-style scaling).
void csr_row_norms1(const int64_t* indptr, const double* data,
                    int64_t nrows, double* norms) {
    for (int64_t i = 0; i < nrows; ++i) {
        double s = 0.0;
        for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p)
            s += data[p] < 0 ? -data[p] : data[p];
        norms[i] = s;
    }
}

// Greedy (Vanek) smoothed-aggregation pass over a CSR strength graph.
// agg (nrows, preallocated) receives the aggregate id per node; returns the
// aggregate count. Three passes: seed aggregates from nodes with no
// aggregated strong neighbor; attach leftovers to a neighboring aggregate
// (decided against the pass-1 state so attachments don't chain); sweep
// remaining islands into new aggregates. Used by the AMG (PCGAMG-analog)
// setup, where per-row Python loops dominate at large n.
int64_t csr_aggregate(const int64_t* indptr, const int32_t* indices,
                      int64_t nrows, int64_t* agg) {
    for (int64_t i = 0; i < nrows; ++i) agg[i] = -1;
    int64_t nagg = 0;
    for (int64_t i = 0; i < nrows; ++i) {
        if (agg[i] != -1) continue;
        bool free_nbhd = true;
        for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) {
            const int32_t j = indices[p];
            if (j != i && agg[j] != -1) { free_nbhd = false; break; }
        }
        if (!free_nbhd) continue;
        agg[i] = nagg;
        for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) {
            const int32_t j = indices[p];
            if (j != i) agg[j] = nagg;
        }
        ++nagg;
    }
    std::vector<int64_t> attach(agg, agg + nrows);
    for (int64_t i = 0; i < nrows; ++i) {
        if (agg[i] != -1) continue;
        for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) {
            const int32_t j = indices[p];
            if (j != i && agg[j] != -1) { attach[i] = agg[j]; break; }
        }
    }
    std::memcpy(agg, attach.data(), nrows * sizeof(int64_t));
    for (int64_t i = 0; i < nrows; ++i) {
        if (agg[i] != -1) continue;
        agg[i] = nagg;
        for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) {
            const int32_t j = indices[p];
            if (agg[j] == -1) agg[j] = nagg;
        }
        ++nagg;
    }
    return nagg;
}

// Reference SpMV (oracle/debug; the production SpMV runs on TPU).
void csr_spmv(const int64_t* indptr, const int32_t* indices,
              const double* data, int64_t nrows, const double* x,
              double* y) {
    for (int64_t i = 0; i < nrows; ++i) {
        double s = 0.0;
        for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p)
            s += data[p] * x[indices[p]];
        y[i] = s;
    }
}

}  // extern "C"
